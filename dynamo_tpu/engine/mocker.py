"""MockEngine: a simulated paged-KV engine (no JAX import).

Role-equivalent of lib/llm/src/mocker/* (MockVllmEngine engine.rs:60,
watermark Scheduler scheduler.rs:197, simulated KvManager kv_manager.rs:524,
LRU evictor): real block bookkeeping with prefix reuse, LRU eviction, and
genuine KV store/remove events — but fake compute, timed by a cost model
(quadratic prefill + linear decode, scheduler.rs:28-43). Lets the KV router,
disagg router, and planner run end-to-end with zero chips.

Disaggregation: with a `remote_prefill_client` wired, prompts at or past
`disagg_threshold` ship to the prefill fleet (`MockPrefillEngine` is the
prefill-role twin, streaming KvStreamFrames chunk by chunk) — the zero-chip
version of the streaming-disagg graph, so routing, the KV data plane, and
the telemetry plane can be exercised end-to-end with fake compute.

Telemetry: per-request phase spans (queue_wait, prefill, remote_prefill,
kv_land per streamed frame, decode) plus deadline/preemption span events —
all behind the `DYN_TRACE` flag, zero-cost when off.
"""

from __future__ import annotations

import asyncio
import bisect
import collections
import itertools
import os
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Optional

from dynamo_tpu import qos
from dynamo_tpu.pipeline.context import Context
from dynamo_tpu.runtime import clock as dclock
from dynamo_tpu.telemetry import brownout as dbrownout
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.telemetry import provenance as dprov
from dynamo_tpu.telemetry import trace as dtrace
from dynamo_tpu.telemetry.goodput import GoodputLedger
from dynamo_tpu.telemetry.histogram import PhaseHistograms
from dynamo_tpu.testing import faults
from dynamo_tpu.tokens import TokenBlockSequence


@dataclass
class MockEngineArgs:
    """Mirrors reference mocker/protocols.rs:160 MockEngineArgs."""

    num_blocks: int = 1024
    block_size: int = 16
    max_batch: int = 64
    watermark: float = 0.01  # fraction of blocks kept free for decode growth
    speedup_ratio: float = 100.0  # sim time = real time / speedup
    # cost model (seconds at speedup 1): prefill a*n + b*n^2, decode per-tok c
    prefill_linear_s: float = 0.0001
    prefill_quadratic_s: float = 1e-8
    decode_per_token_s: float = 0.01
    # Unified mixed steps (ISSUE 16, parity with JaxEngineConfig): per-
    # iteration prefill token budget riding along the decode batch in one
    # simulated dispatch (cost = the slower of the two halves — the chunk
    # hides behind decode or vice versa). 0 = legacy whole-prompt prefill
    # at admission; brownout's chunk_cap rung halves the effective value,
    # latched once per iteration.
    chunk_budget: int = 0
    dp_rank: Optional[int] = None
    # preemption-storm guard (parity with JaxEngineConfig)
    max_preemptions: int = field(
        default_factory=lambda: int(os.environ.get("DYN_MAX_PREEMPTIONS", "8"))
    )
    preempt_backoff_ms: float = field(
        default_factory=lambda: float(
            os.environ.get("DYN_PREEMPT_BACKOFF_MS", "25")
        )
    )


class _SimKvCache:
    """Paged cache with hash-chain prefix reuse + LRU eviction, emitting
    real KV events (reference mocker/kv_manager.rs:524)."""

    def __init__(
        self,
        args: MockEngineArgs,
        on_stored: Optional[Callable[[list[dict]], None]] = None,
        on_removed: Optional[Callable[[list[int]], None]] = None,
    ) -> None:
        self.args = args
        self.free_blocks = args.num_blocks
        # block_hash -> refcount; 0-ref blocks stay cached until evicted
        self.refs: dict[int, int] = {}
        self.lru: collections.OrderedDict[int, None] = collections.OrderedDict()
        self.on_stored = on_stored
        self.on_removed = on_removed

    @property
    def used_blocks(self) -> int:
        return self.args.num_blocks - self.free_blocks

    @property
    def usage(self) -> float:
        return self.used_blocks / max(1, self.args.num_blocks)

    @property
    def available_blocks(self) -> int:
        """Free + evictable (cached but unreferenced) blocks."""
        return self.free_blocks + sum(
            1 for h in self.lru if self.refs.get(h) == 0
        )

    def cached_prefix_blocks(self, hashes: list[int]) -> int:
        n = 0
        for h in hashes:
            if h in self.refs:
                n += 1
            else:
                break
        return n

    def _evict(self, need: int, protected: frozenset = frozenset()) -> bool:
        evicted: list[int] = []
        skipped: list[int] = []
        while need > 0 and self.lru:
            h, _ = self.lru.popitem(last=False)
            if h in protected:
                # cached block of the request being admitted — evicting it
                # would un-cache what we just counted as a prefix hit
                skipped.append(h)
                continue
            if self.refs.get(h, 1) == 0:
                del self.refs[h]
                self.free_blocks += 1
                evicted.append(h)
                need -= 1
        for h in skipped:
            self.lru[h] = None
        if evicted and self.on_removed:
            self.on_removed(evicted)
        return need <= 0

    def try_allocate(self, hashes: list[int], extra_unique: int) -> bool:
        """Acquire refs on all chain blocks (+unique partial blocks)."""
        new_hashes = [h for h in hashes if h not in self.refs]
        need = len(new_hashes) + extra_unique
        if need > self.free_blocks and not self._evict(
            need - self.free_blocks, frozenset(hashes)
        ):
            return False
        stored: list[dict] = []
        parent = 0
        for h in hashes:
            if h in self.refs:
                self.refs[h] += 1
                self.lru.pop(h, None)
            else:
                self.refs[h] = 1
                self.free_blocks -= 1
                stored.append({"block_hash": h, "parent_hash": parent})
            parent = h
        self.free_blocks -= extra_unique
        if stored and self.on_stored:
            self.on_stored(stored)
        return True

    def grow(self, new_blocks: list) -> bool:
        """A decode step completed new block(s) (TokenBlock instances)."""
        stored = []
        for b in new_blocks:
            h = b.block_hash
            if h in self.refs:
                self.refs[h] += 1
                self.lru.pop(h, None)
            else:
                if self.free_blocks <= 0 and not self._evict(1):
                    return False
                self.refs[h] = 1
                self.free_blocks -= 1
                stored.append({"block_hash": h, "parent_hash": b.parent_hash})
        if stored and self.on_stored:
            self.on_stored(stored)
        return True

    def release(self, hashes: list[int], unique: int) -> None:
        """Drop refs; 0-ref blocks become evictable (stay cached)."""
        for h in hashes:
            n = self.refs.get(h)
            if n is None:
                continue
            if n <= 1:
                self.refs[h] = 0
                self.lru[h] = None
                self.lru.move_to_end(h)
            else:
                self.refs[h] = n - 1
        self.free_blocks += unique


@dataclass
class _MockSeq:
    request: PreprocessedRequest
    context: Context
    out: asyncio.Queue
    hash_seq: TokenBlockSequence
    generated: int = 0
    prompt_len: int = 0  # original prompt length (< len(token_ids) on resume)
    acquired_hashes: list[int] = field(default_factory=list)
    unique_blocks: int = 1
    remote_prefilled: bool = False  # KV arrived from the prefill fleet
    prefill_remaining: int = 0  # unprefilled prompt tokens (mixed-step mode)
    spans: dict = field(default_factory=dict)  # open telemetry phase spans
    # QoS plane (parity with JaxEngine._Sequence)
    priority: str = qos.DEFAULT_CLASS
    rank: int = qos.CLASS_RANK[qos.DEFAULT_CLASS]
    arrival_order: int = 0
    preemptions: int = 0
    requeue_after: float = 0.0
    # always-on phase-timing marks (feed the engine's phase histograms)
    t_arrival: float = 0.0
    t_admitted: Optional[float] = None
    t_first: Optional[float] = None
    t_last: Optional[float] = None

    @property
    def prompt(self) -> list[int]:
        return self.request.token_ids[: self.prompt_len]


class MockFleetPrefixRegistry:
    """Zero-chip twin of the PeerBlockService/Client advert plane (fleet
    prefix cache): each registered MockEngine's _SimKvCache IS its
    advertised block inventory, and a "pull" is a simulated transfer
    (`pull_block_s` per block) that lets the pulling engine skip
    recomputing those prefix tokens. Fenced peers are never pulled from
    (counted as the fenced fallback when they were the only holder), and
    `fail_every` fails every Nth pull attempt deterministically — no RNG,
    so replay stays bit-identical — exercising the fallback-to-recompute
    path. Only prefill ACCOUNTING changes on any outcome; the token
    stream is identical either way (token-identity invariant)."""

    def __init__(
        self, pull_block_s: float = 0.0005, fail_every: int = 0
    ) -> None:
        self.engines: list["MockEngine"] = []
        self.pull_block_s = pull_block_s
        self.fail_every = max(0, int(fail_every))
        self._attempts = 0
        self.pulled_blocks = 0
        self.pull_outcomes: dict[str, int] = {}

    def register(self, engine: "MockEngine") -> None:
        self.engines.append(engine)
        engine.peer_registry = self

    def _note(self, engine: "MockEngine", outcome: str, blocks: int) -> None:
        if blocks <= 0:
            return
        self.pull_outcomes[outcome] = (
            self.pull_outcomes.get(outcome, 0) + blocks
        )
        engine.pull_outcomes[outcome] = (
            engine.pull_outcomes.get(outcome, 0) + blocks
        )

    def pull(
        self, engine: "MockEngine", hashes: list[int], cached: int
    ) -> tuple[int, float]:
        """(blocks pulled past `engine`'s local `cached` prefix, simulated
        transfer cost). 0 blocks on miss/failure — the engine recomputes."""
        best = fenced_best = 0
        for peer in self.engines:
            if peer is engine:
                continue
            n = peer.cache.cached_prefix_blocks(hashes)
            if peer.fenced:
                fenced_best = max(fenced_best, n)
            else:
                best = max(best, n)
        gap = best - cached
        if gap <= 0:
            if fenced_best > cached:
                # the only holder is fenced: never pull from a zombie
                self._note(engine, "fallback_fenced", fenced_best - cached)
            return 0, 0.0
        self._attempts += 1
        if self.fail_every and self._attempts % self.fail_every == 0:
            self._note(engine, "fallback_error", gap)
            return 0, 0.0
        self.pulled_blocks += gap
        self._note(engine, "pulled", gap)
        return gap, gap * self.pull_block_s


class MockEngine:
    """AsyncEngine-compatible: generate(request, context) -> LLMEngineOutput
    stream, same surface as JaxEngine/EchoEngine."""

    def __init__(
        self,
        args: Optional[MockEngineArgs] = None,
        on_blocks_stored: Optional[Callable[[list[dict]], None]] = None,
        on_blocks_removed: Optional[Callable[[list[int]], None]] = None,
        remote_prefill_client: Optional[Any] = None,
        disagg_threshold: Optional[int] = None,
        peer_registry: Optional[MockFleetPrefixRegistry] = None,
    ) -> None:
        self.args = args or MockEngineArgs()
        self.cache = _SimKvCache(self.args, on_blocks_stored, on_blocks_removed)
        self.active: list[_MockSeq] = []
        # priority-then-deadline ordered admission queue (kept sorted by
        # _enqueue — parity with JaxEngine.waiting)
        self.waiting: list[_MockSeq] = []
        self._arrivals = itertools.count(1)
        self._loop_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self.generated_tokens = 0
        # cumulative UNCACHED prompt tokens actually prefilled; the routing
        # tests compare this (deterministic) rather than wall-clock TTFT
        self.prefilled_tokens = 0
        # lifeguard counters (same names the JaxEngine stats carry)
        self.deadline_exceeded = 0
        self.injected_aborts = 0
        # QoS counters + brownout rung (parity with EngineStats)
        self.preemptions_by_class: dict[str, int] = {}
        self.preempted_too_often = 0
        self.shed_brownout = 0
        self.brownout_level = 0
        self.spec_paused = False  # recorded for parity (mocker has no spec)
        self.fenced = False  # self-fenced on primary-lease loss
        # streaming-disagg: prompts >= threshold ship to the prefill fleet
        self.remote_prefill_client = remote_prefill_client
        self.disagg_threshold = disagg_threshold or 2 * self.args.block_size
        self.remote_prefills = 0
        self.kv_frames_rx = 0
        # fleet prefix cache (zero-chip): pulls ride the shared registry
        self.peer_registry = peer_registry
        if peer_registry is not None and self not in peer_registry.engines:
            peer_registry.engines.append(self)
        self.kv_pulled_blocks = 0
        self.pull_outcomes: dict[str, int] = {}
        # always-on per-phase latency distributions (same instrumentation
        # points as the DYN_TRACE spans, but distribution-valued and never
        # gated) — ride stats() -> ForwardPassMetrics to the fleet planes
        self.phase_hist = PhaseHistograms()
        # goodput ledger (ISSUE 14 parity with EngineStats.goodput): steps
        # recorded in SIMULATED seconds (the deterministic cost model, not
        # wall clock) so fleet-vs-direct comparisons are exact
        self.goodput = GoodputLedger()
        # trace process track (set by the worker host; None = process name)
        self.trace_proc: Optional[str] = None

    # Hook properties matching JaxEngine's surface so worker hosting can
    # attach a KvEventPublisher uniformly (entrypoint/inputs.py).
    @property
    def on_blocks_stored(self):
        return self.cache.on_stored

    @on_blocks_stored.setter
    def on_blocks_stored(self, fn) -> None:
        self.cache.on_stored = fn

    @property
    def on_blocks_removed(self):
        return self.cache.on_removed

    @on_blocks_removed.setter
    def on_blocks_removed(self, fn) -> None:
        self.cache.on_removed = fn

    # ----------------------------------------------------------- telemetry

    def _sp_begin(self, seq: _MockSeq, name: str, **attrs) -> None:
        sp = dtrace.begin(name, ctx=seq.context, proc=self.trace_proc, **attrs)
        if sp is not None:
            seq.spans[name] = sp

    def _sp_finish(self, seq: _MockSeq, name: str, **attrs) -> None:
        dtrace.finish(seq.spans.pop(name, None), **attrs)

    def _sp_event(self, seq: _MockSeq, name: str, **attrs) -> None:
        for sp in seq.spans.values():
            sp.event(name, **attrs)
            return

    def _sp_close_all(self, seq: _MockSeq) -> None:
        for name in list(seq.spans):
            self._sp_finish(seq, name)

    # ------------------------------------------------------------- public

    def _observe_stream(self, seq: _MockSeq, item: LLMEngineOutput) -> None:
        """Always-on phase histogram recording at the stream edge (same
        contract as JaxEngine._observe_stream)."""
        ph = self.phase_hist
        now = dclock.now()
        if item.token_ids:
            if seq.t_first is None:
                seq.t_first = now
                ph.observe("ttft", (now - seq.t_arrival) * 1e3)
                if seq.t_admitted is not None:
                    ph.observe("prefill", (now - seq.t_admitted) * 1e3)
            elif seq.t_last is not None:
                ph.observe("inter_token", (now - seq.t_last) * 1e3)
            seq.t_last = now
        if item.finish_reason is not None:
            ph.observe("e2e", (now - seq.t_arrival) * 1e3)

    async def generate(
        self, request: PreprocessedRequest, context: Optional[Context] = None
    ) -> AsyncIterator[LLMEngineOutput]:
        t_arrival = dclock.now()
        ctx = context or Context()
        if self.fenced:
            yield LLMEngineOutput.final_error(
                ctx.id, "admission",
                "worker is fenced (primary lease lost); request must be "
                "served elsewhere",
                "worker_fenced",
            )
            return
        if ctx.expired() or ctx.ttft_expired():
            self.deadline_exceeded += 1
            yield LLMEngineOutput.final_error(
                ctx.id, "admission", "deadline expired before admission",
                "deadline_exceeded",
            )
            return
        priority = qos.priority_of(ctx, request)
        if self.brownout_level and priority in dbrownout.shed_classes_for(
            self.brownout_level
        ):
            self.shed_brownout += 1
            yield LLMEngineOutput.final_error(
                ctx.id, "admission",
                f"brownout level {self.brownout_level} "
                f"({dbrownout.LADDER[self.brownout_level]}) sheds "
                f"{priority}-class requests",
                "brownout_shed",
            )
            return
        # in-flight migration replay (see JaxEngine._Sequence): the tail of
        # token_ids past resume_prompt_len was already streamed by a dead
        # worker; counting it as generated keeps the deterministic token
        # cycle and the max_tokens budget identical to an unfaulted run
        prompt_len = len(request.token_ids)
        resume = int(request.extra.get("resume_prompt_len") or 0)
        if 0 < resume < prompt_len:
            # replayed tail: already streamed by a dead worker, but its KV
            # must be re-prefilled here (goodput taxonomy: migration)
            self.goodput.record_waste(
                "migration_replay", prompt_len - resume
            )
            prompt_len = resume
        first_remote: Optional[int] = None
        if (
            self.remote_prefill_client is not None
            and resume == 0
            and prompt_len >= self.disagg_threshold
        ):
            first_remote = await self._remote_prefill(request, ctx)
            if first_remote is None and (ctx.is_killed() or ctx.is_stopped()):
                yield LLMEngineOutput.final(FinishReason.CANCELLED)
                return
        seq = _MockSeq(
            request=request,
            context=ctx,
            out=asyncio.Queue(),
            prompt_len=prompt_len,
            generated=len(request.token_ids) - prompt_len,
            hash_seq=TokenBlockSequence(
                block_size=self.args.block_size,
                tokens=list(request.token_ids),
            ),
            t_arrival=t_arrival,
            priority=priority,
            rank=qos.rank_of(priority),
        )
        if first_remote is not None:
            # the prefill worker sampled the first token (the same
            # deterministic cycle value the local path would produce);
            # count it against the budget and continue decode after it
            self.remote_prefills += 1
            seq.remote_prefilled = True
            seq.generated += 1
            self.generated_tokens += 1
            max_tokens = request.stop.max_tokens or 64
            if seq.generated >= max_tokens:
                yield LLMEngineOutput(
                    token_ids=[first_remote],
                    finish_reason=FinishReason.LENGTH,
                )
                return
            seq.out.put_nowait(LLMEngineOutput(token_ids=[first_remote]))
        if dtrace.enabled():
            self._sp_begin(
                seq, "queue_wait", tokens=prompt_len, priority=seq.priority
            )
        self._enqueue(seq)
        self._wake.set()
        self._ensure_loop()
        try:
            while True:
                item = await seq.out.get()
                self._observe_stream(seq, item)
                yield item
                if item.finish_reason is not None:
                    return
        finally:
            # consumer disconnected mid-stream: mark the request dead so the
            # sim loop releases its cache blocks instead of generating into
            # a queue nobody reads (mirrors JaxEngine.generate)
            ctx.kill()
            self._wake.set()

    async def _remote_prefill(
        self, request: PreprocessedRequest, ctx: Context
    ) -> Optional[int]:
        """Ship the prompt to the prefill fleet over the streaming KV data
        plane; returns the remotely-sampled first token, or None to fall
        back to the local (simulated) prefill path."""
        frames = 0
        with dtrace.span(
            "remote_prefill", ctx=ctx, proc=self.trace_proc,
            tokens=len(request.token_ids),
        ) as rsp:
            async def on_frame(frame) -> None:
                nonlocal frames
                frames += 1
                self.kv_frames_rx += 1
                # sim engine: nothing to inject (the cache is hash-based);
                # the span records when/that each frame landed
                with dtrace.span(
                    "kv_land", parent=rsp, proc=self.trace_proc,
                    seq=frame.seq, blocks=frame.payload.num_blocks,
                ):
                    pass

            extra = None
            if rsp.trace_id:
                extra = {"trace": {"tid": rsp.trace_id, "sid": rsp.span_id}}
            try:
                resp = await self.remote_prefill_client.prefill(
                    list(request.token_ids),
                    cached_blocks=0,
                    stream=True,
                    on_frame=on_frame,
                    deadline=ctx.deadline,
                    ctx=ctx,
                    extra=extra,
                )
            except Exception:  # noqa: BLE001 — disagg is an optimization
                rsp.set(fallback="transfer_failed")
                return None
            rsp.set(frames=frames)
            if resp is None or resp.error or resp.first_token < 0:
                rsp.set(fallback=resp.code if resp else "no_response")
                return None
            rsp.set(streamed_blocks=resp.streamed_blocks)
            return int(resp.first_token)

    def stats(self) -> dict:
        return {
            "active_slots": len(self.active),
            "total_slots": self.args.max_batch,
            "waiting": len(self.waiting),
            "used_blocks": self.cache.used_blocks,
            "total_blocks": self.args.num_blocks,
            "cache_usage": self.cache.usage,
            "deadline_exceeded": self.deadline_exceeded,
            "phase_histograms": self.phase_hist,
            "preemptions_by_class": dict(self.preemptions_by_class),
            "preempted_too_often": self.preempted_too_often,
            "shed_brownout": self.shed_brownout,
            "brownout_level": self.brownout_level,
            "goodput": self.goodput,
            "kv_pulled_blocks": self.kv_pulled_blocks,
            "kv_pull_outcomes": dict(self.pull_outcomes),
        }

    def apply_brownout(self, level: int) -> None:
        """Brownout-ladder rung (parity with JaxEngine.apply_brownout):
        >= 1 sheds bulk arrivals, >= 2 records spec pause (the mocker has
        no drafter — the flag exists so the policy is testable
        engine-free), >= 4 sheds standard arrivals too."""
        self.brownout_level = max(0, int(level))
        self.spec_paused = self.brownout_level >= 2

    async def close(self) -> None:
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
            self._loop_task = None

    # -------------------------------------------------------------- sched

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.create_task(self._run())

    @staticmethod
    def _queue_key(seq: _MockSeq) -> tuple:
        dl = seq.context.deadline
        return (seq.rank, dl if dl is not None else float("inf"),
                seq.arrival_order)

    def _enqueue(self, seq: _MockSeq) -> None:
        if not seq.arrival_order:
            seq.arrival_order = next(self._arrivals)
        bisect.insort(self.waiting, seq, key=self._queue_key)

    async def _sim_sleep(self, sim_s: float) -> None:
        await asyncio.sleep(sim_s / self.args.speedup_ratio)

    def _admit(self) -> float:
        """Watermark admission (scheduler.rs:197); returns prefill sim-cost."""
        cost = 0.0
        n_prefill_total = 0
        watermark_blocks = int(self.args.num_blocks * self.args.watermark)
        # reap abandoned requests before they consume sim capacity
        for seq in [s for s in self.waiting if s.context.is_killed()]:
            self.waiting.remove(seq)
            self._sp_close_all(seq)
            seq.out.put_nowait(LLMEngineOutput.final(FinishReason.CANCELLED))
        # shed queued requests past their deadline / TTFT budget
        for seq in [
            s for s in self.waiting
            if s.context.expired() or s.context.ttft_expired()
        ]:
            self.waiting.remove(seq)
            self.deadline_exceeded += 1
            seq.context.kill()
            self._sp_event(seq, "deadline_exceeded", phase="queue")
            self._sp_close_all(seq)
            seq.out.put_nowait(
                LLMEngineOutput.final_error(
                    seq.context.id, "queue",
                    "deadline exceeded while queued", "deadline_exceeded",
                )
            )
        idx = 0
        while idx < len(self.waiting) and len(self.active) < self.args.max_batch:
            seq = self.waiting[idx]
            if seq.requeue_after and dclock.now() < seq.requeue_after:
                # preemption re-admission backoff: don't head-block others
                idx += 1
                continue
            hashes = [b.block_hash for b in seq.hash_seq.blocks]
            cached = self.cache.cached_prefix_blocks(hashes)
            if (
                self.cache.available_blocks - (len(hashes) - cached)
                < watermark_blocks
            ):
                break
            if not self.cache.try_allocate(hashes, extra_unique=1):
                break
            self.waiting.pop(idx)
            if seq.t_admitted is None:  # first admission (not a resume)
                seq.t_admitted = dclock.now()
                self.phase_hist.observe(
                    "queue_wait", (seq.t_admitted - seq.t_arrival) * 1e3
                )
            seq.acquired_hashes = list(hashes)
            self.active.append(seq)
            pulled = 0
            if (
                self.peer_registry is not None
                and not seq.remote_prefilled
                and cached < len(hashes)
            ):
                # fleet prefix pull: a peer's cache may hold the rest of
                # the prefix — pulled blocks skip prefill compute; the
                # simulated transfer cost joins this admission's dispatch
                # (so a kill/blackout wave can land MID-pull)
                pulled, pull_cost = self.peer_registry.pull(
                    self, hashes, cached
                )
                if pulled:
                    self.kv_pulled_blocks += pulled
                    cost += pull_cost
            if seq.remote_prefilled:
                # KV already arrived over the streaming data plane — no
                # local prefill compute to simulate
                n_prefill = 0
            else:
                n_prefill = max(0, len(seq.request.token_ids)
                                - (cached + pulled) * self.args.block_size)
            self.prefilled_tokens += n_prefill
            if self.args.chunk_budget > 0:
                # mixed-step mode: prefill compute rides along future
                # decode iterations chunk-by-chunk instead of blocking
                # the whole batch at admission (always assigned: a
                # preempted victim re-admitted fully-cached must clear
                # any stale remainder)
                seq.prefill_remaining = n_prefill
            else:
                n_prefill_total += n_prefill
                cost += (
                    self.args.prefill_linear_s * n_prefill
                    + self.args.prefill_quadratic_s * n_prefill * n_prefill
                )
            if seq.spans:
                self._sp_finish(
                    seq, "queue_wait", cached_blocks=cached
                )
                if n_prefill:
                    self._sp_begin(seq, "prefill", tokens=n_prefill)
                else:
                    self._sp_begin(seq, "decode")
        if cost > 0:
            # one simulated prefill "dispatch" for the admitted batch,
            # recorded in sim-seconds (deterministic cost model)
            self.goodput.record_step(
                "prefill", cost, prefill_tokens=n_prefill_total
            )
        return cost

    def _chunk_budget(self) -> int:
        """Per-iteration prefill token budget (mixed-step mode).

        Brownout's chunk_cap rung halves it (floored at one KV block);
        the caller latches the value ONCE at the top of each loop
        iteration — parity with JaxEngine's step-boundary latch, so a
        brownout transition landing mid-iteration never re-slices work
        the iteration already planned."""
        return qos.effective_chunk_budget(
            self.args.chunk_budget,
            chunk_cap=dbrownout.chunk_capped(self.brownout_level),
            block_size=self.args.block_size,
        )

    async def _run(self) -> None:
        while True:
            if not self.active and not self.waiting:
                self._wake.clear()
                await self._wake.wait()
            chunk_budget = self._chunk_budget()  # step-boundary latch
            prefill_cost = self._admit()
            if prefill_cost:
                await self._sim_sleep(prefill_cost)
            for seq in self.active:
                if "prefill" in seq.spans and not seq.prefill_remaining:
                    self._sp_finish(seq, "prefill")
                    self._sp_begin(seq, "decode")
            if not self.active:
                # blocked: waiting head cannot be admitted yet
                if self.waiting:
                    await asyncio.sleep(0.001)
                continue
            # mixed-step packing: decode lanes keep stepping while queued
            # prefill work drains chunk-by-chunk under the latched budget
            # (priority order — same key the admission queue sorts by)
            decoding = [s for s in self.active if not s.prefill_remaining]
            prefilling = sorted(
                (s for s in self.active if s.prefill_remaining > 0),
                key=self._queue_key,
            )
            chunk_tokens = 0
            slots = 0
            budget = chunk_budget
            for seq in prefilling:
                if budget <= 0:
                    break
                n = min(seq.prefill_remaining, budget)
                seq.prefill_remaining -= n
                budget -= n
                chunk_tokens += n
                slots += 1
                if not seq.prefill_remaining and "prefill" in seq.spans:
                    self._sp_finish(seq, "prefill")
                    self._sp_begin(seq, "decode")
            chunk_cost = (
                self.args.prefill_linear_s * chunk_tokens
                + self.args.prefill_quadratic_s * chunk_tokens * chunk_tokens
            )
            # one decode iteration for the whole batch (a gray-worker
            # fault stretches the simulated step: slow, never dead)
            step_s = self.args.decode_per_token_s
            if faults.active():
                inj = faults.get_injector()
                if inj is not None:
                    await inj.on_dispatch()
                    step_s *= inj.dispatch_slow_factor()
            if decoding and chunk_tokens:
                # unified device step: the chunk hides behind the decode
                # half (or vice versa) — cost is the slower of the two
                step_s = max(step_s, chunk_cost)
                await self._sim_sleep(step_s)
                self.goodput.record_step(
                    f"mixed_step@c{slots}",
                    step_s,
                    lanes=len(decoding),
                    capacity=self.args.max_batch,
                    prefill_tokens=chunk_tokens,
                )
            elif chunk_tokens:
                await self._sim_sleep(chunk_cost)
                self.goodput.record_step(
                    "prefill_chunk", chunk_cost,
                    prefill_tokens=chunk_tokens,
                )
            else:
                await self._sim_sleep(step_s)
                self.goodput.record_step(
                    "decode",
                    step_s,
                    lanes=len(decoding),
                    capacity=self.args.max_batch,
                )
            # deadline expiry mid-generation: cancel + structured error
            for seq in [
                s for s in list(self.active) if s.context.expired()
            ]:
                self.deadline_exceeded += 1
                # partial output discarded (goodput taxonomy: deadline)
                self.goodput.record_waste("deadline_partial", seq.generated)
                seq.context.kill()
                self.active.remove(seq)
                self.cache.release(seq.acquired_hashes, seq.unique_blocks)
                self._sp_event(seq, "deadline_exceeded", phase="decode")
                self._sp_close_all(seq)
                seq.out.put_nowait(
                    LLMEngineOutput.final_error(
                        seq.context.id, "decode",
                        "deadline exceeded mid-generation",
                        "deadline_exceeded",
                    )
                )
            for seq in decoding:
                # lanes still mid-prefill emit no tokens this iteration
                self._step_seq(seq)

    def _abort_all(self, cause: str, code: str = "injected_fault") -> None:
        """Injected crash (faults.abort_after_tokens) or self-fence: fail
        every live sequence with a structured error and release every
        cache ref — the simulated twin of a worker process dying (or
        being fenced) mid-stream."""
        if code == "injected_fault":
            self.injected_aborts += 1
        for seq in list(self.waiting):
            self.waiting.remove(seq)
            self._sp_close_all(seq)
            seq.out.put_nowait(
                LLMEngineOutput.final_error(
                    seq.context.id, "queue", cause, code
                )
            )
        for seq in list(self.active):
            self.active.remove(seq)
            self.cache.release(seq.acquired_hashes, seq.unique_blocks)
            self._sp_close_all(seq)
            seq.out.put_nowait(
                LLMEngineOutput.final_error(
                    seq.context.id, "decode", cause, code
                )
            )

    def fence(self, reason: str) -> None:
        """Worker self-fence (parity with JaxEngine.fence): the primary
        lease is gone — stop admitting, fail every lane with a structured
        `worker_fenced` error between simulated steps, and decode no more."""
        if self.fenced:
            return
        self.fenced = True
        dtrace.event("worker_fenced", reason=reason)
        self._abort_all(f"worker fenced: {reason}", code="worker_fenced")
        if self._loop_task is not None:
            self._loop_task.cancel()
            self._loop_task = None

    def _step_seq(self, seq: _MockSeq) -> None:
        if seq not in self.active:
            # released mid-iteration (an injected abort earlier in this
            # batch step): stepping a zombie would re-acquire cache refs
            return
        if faults.active():
            inj = faults.get_injector()
            if inj is not None and inj.on_token():
                self._abort_all("injected engine fault (abort_after_tokens)")
                return
        # Deterministic fake token: cycle over the ORIGINAL prompt (on a
        # migration replay, token_ids carries already-emitted output too —
        # cycling over it would diverge from the unfaulted run)
        prompt = seq.prompt
        tok = prompt[seq.generated % max(1, len(prompt))]
        seq.generated += 1
        self.generated_tokens += 1
        self.goodput.record_decode_tokens()
        prev_blocks = len(seq.hash_seq.blocks)
        seq.hash_seq.append(tok)
        new_blocks = seq.hash_seq.blocks[prev_blocks:]
        if new_blocks:
            if not self.cache.grow(new_blocks):
                self._preempt_for(seq)
                return
            seq.acquired_hashes.extend(b.block_hash for b in new_blocks)
        max_tokens = seq.request.stop.max_tokens or 64
        finished = seq.generated >= max_tokens or seq.context.is_stopped()
        reason = None
        if finished:
            reason = (
                FinishReason.CANCELLED
                if seq.context.is_stopped()
                else FinishReason.LENGTH
            )
            if reason is FinishReason.CANCELLED:
                # consumer disconnected mid-stream (goodput taxonomy:
                # cancelled partial — same attribution as JaxEngine)
                self.goodput.record_waste(
                    "cancelled_partial", seq.generated
                )
        seq.out.put_nowait(
            LLMEngineOutput(
                token_ids=[tok],
                finish_reason=reason,
            )
        )
        if finished:
            self.active.remove(seq)
            self.cache.release(seq.acquired_hashes, seq.unique_blocks)
            if seq.spans:
                self._sp_finish(seq, "decode", tokens=seq.generated)
                self._sp_close_all(seq)

    def _preempt_for(self, seq: _MockSeq) -> None:
        """Class-aware victim choice (parity with JaxEngine._preempt_victim):
        lowest class first, youngest within a class, never a victim whose
        class strictly outranks the grower's — the grower yields itself
        when everyone else is more important."""
        victim = None
        worst = max(qos.CLASS_RANK.values())
        for rank in range(worst, seq.rank - 1, -1):
            for cand in reversed(self.active):
                if cand is seq or cand.rank != rank:
                    continue
                victim = cand
                break
            if victim is not None:
                break
        chosen = victim if victim is not None else seq
        if dprov.enabled():
            dprov.record(
                "engine", "preempt", chosen.priority,
                reason="self_yield" if victim is None else "class_rank",
                ctx=chosen.context,
                proc=self.trace_proc,
                alternatives=[
                    {
                        "request": c.context.id,
                        "class": c.priority,
                        "rank": c.rank,
                        "generated": c.generated,
                    }
                    for c in self.active
                    if c is not seq
                ][:8],
                grower=seq.context.id,
                grower_class=seq.priority,
            )
        self._preempt_seq(chosen)

    def _preempt_seq(self, victim: _MockSeq) -> None:
        if victim in self.active:
            self.active.remove(victim)
        self.cache.release(victim.acquired_hashes, victim.unique_blocks)
        victim.acquired_hashes = []
        victim.preemptions += 1
        self.preemptions_by_class[victim.priority] = (
            self.preemptions_by_class.get(victim.priority, 0) + 1
        )
        # every token whose simulated KV this preemption released must be
        # recomputed on re-admission (goodput taxonomy: preempt replay)
        self.goodput.record_waste(
            "preempt_replay", victim.prompt_len + victim.generated
        )
        if victim.preemptions > self.args.max_preemptions:
            # preemption-storm guard (parity with JaxEngine._preempt_seq)
            self.preempted_too_often += 1
            self._sp_event(victim, "preempted_too_often")
            self._sp_close_all(victim)
            victim.out.put_nowait(
                LLMEngineOutput.final_error(
                    victim.context.id, "preemption",
                    f"preempted {victim.preemptions} times under sustained "
                    f"pressure (DYN_MAX_PREEMPTIONS="
                    f"{self.args.max_preemptions}); giving up",
                    "preempted_too_often",
                )
            )
            return
        self._sp_event(victim, "preempted", count=victim.preemptions)
        self._sp_finish(victim, "decode", preempted=True)
        backoff_s = min(
            2.0,
            self.args.preempt_backoff_ms
            / 1e3
            * (1 << (victim.preemptions - 1)),
        )
        if dprov.enabled():
            dprov.record(
                "engine", "readmit", victim.priority,
                reason="backoff",
                ctx=victim.context,
                proc=self.trace_proc,
                backoff_ms=round(backoff_s * 1e3, 3),
                preemptions=victim.preemptions,
            )
        victim.requeue_after = dclock.now() + backoff_s
        self._enqueue(victim)


class MockPrefillEngine:
    """Prefill-role twin of MockEngine for the streaming-disagg mocker
    graph: serves RemotePrefillRequests under the same cost model with
    fake (but correctly-shaped, codec-exercising) KV payloads, streaming
    one KvStreamFrame per chunk of completed blocks. First-token sampling
    follows the mocker's deterministic cycle (prompt[0]), so a disagg
    mocker stream is token-identical to the aggregated mocker."""

    def __init__(
        self,
        args: Optional[MockEngineArgs] = None,
        chunk_blocks: int = 2,
    ) -> None:
        self.args = args or MockEngineArgs()
        self.chunk_blocks = max(1, chunk_blocks)
        self.served = 0
        self.frames_emitted = 0
        self.trace_proc: Optional[str] = None

    async def _sim_sleep(self, sim_s: float) -> None:
        await asyncio.sleep(sim_s / self.args.speedup_ratio)

    def _chunk_cost(self, n_tokens: int) -> float:
        return (
            self.args.prefill_linear_s * n_tokens
            + self.args.prefill_quadratic_s * n_tokens * n_tokens
        )

    def _payload(self, nblocks: int, block_size: int):
        import numpy as np

        from dynamo_tpu.disagg.protocols import KvBlockPayload

        k = np.zeros((1, 1, max(1, nblocks), block_size, 1), np.float32)
        return KvBlockPayload.encode(k, k)

    async def prefill_only(self, req: Any) -> Any:
        """Monolithic path: one simulated prefill, one dense payload."""
        from dynamo_tpu.disagg.protocols import RemotePrefillResponse

        await self._sim_sleep(self._chunk_cost(len(req.token_ids)))
        self.served += 1
        bs = req.block_size or self.args.block_size
        total = -(-len(req.token_ids) // bs)
        return RemotePrefillResponse(
            request_id=req.request_id,
            first_token=int(req.token_ids[0]),
            payload=self._payload(total, bs),
            first_block=0,
        )

    async def prefill_only_stream(
        self, req: Any, emit, cancelled=None
    ) -> Optional[Any]:
        """Streaming path: simulate chunked prefill, shipping each chunk's
        completed blocks while the next chunk 'computes'. Returns None on
        requester cancellation (PrefillWorkerService contract)."""
        from dynamo_tpu.disagg.protocols import (
            KvStreamFrame,
            RemotePrefillResponse,
        )

        bs = req.block_size or self.args.block_size
        tokens = list(req.token_ids)
        full_blocks = len(tokens) // bs
        streamed = 0
        seqno = 0
        while streamed < full_blocks:
            if cancelled is not None and cancelled():
                return None
            n = min(self.chunk_blocks, full_blocks - streamed)
            with dtrace.wire_span("prefill_chunk", blocks=n):
                await self._sim_sleep(self._chunk_cost(n * bs))
            await emit(
                KvStreamFrame(
                    request_id=req.request_id,
                    seq=seqno,
                    first_block=streamed,
                    payload=self._payload(n, bs),
                )
            )
            self.frames_emitted += 1
            seqno += 1
            streamed += n
        # tail: the partial block (or the whole prompt when it fits in one)
        tail_tokens = len(tokens) - full_blocks * bs
        with dtrace.wire_span("prefill_chunk", blocks=1, tail=True):
            await self._sim_sleep(self._chunk_cost(max(1, tail_tokens)))
        if cancelled is not None and cancelled():
            return None
        self.served += 1
        return RemotePrefillResponse(
            request_id=req.request_id,
            first_token=int(tokens[0]),
            payload=self._payload(1, bs),
            first_block=streamed,
            streamed_blocks=streamed,
        )
