"""MockEngine: a simulated paged-KV engine (no JAX import).

Role-equivalent of lib/llm/src/mocker/* (MockVllmEngine engine.rs:60,
watermark Scheduler scheduler.rs:197, simulated KvManager kv_manager.rs:524,
LRU evictor): real block bookkeeping with prefix reuse, LRU eviction, and
genuine KV store/remove events — but fake compute, timed by a cost model
(quadratic prefill + linear decode, scheduler.rs:28-43). Lets the KV router,
disagg router, and planner run end-to-end with zero chips.
"""

from __future__ import annotations

import asyncio
import collections
from dataclasses import dataclass, field
from typing import AsyncIterator, Callable, Optional

from dynamo_tpu.pipeline.context import Context
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.testing import faults
from dynamo_tpu.tokens import TokenBlockSequence


@dataclass
class MockEngineArgs:
    """Mirrors reference mocker/protocols.rs:160 MockEngineArgs."""

    num_blocks: int = 1024
    block_size: int = 16
    max_batch: int = 64
    watermark: float = 0.01  # fraction of blocks kept free for decode growth
    speedup_ratio: float = 100.0  # sim time = real time / speedup
    # cost model (seconds at speedup 1): prefill a*n + b*n^2, decode per-tok c
    prefill_linear_s: float = 0.0001
    prefill_quadratic_s: float = 1e-8
    decode_per_token_s: float = 0.01
    dp_rank: Optional[int] = None


class _SimKvCache:
    """Paged cache with hash-chain prefix reuse + LRU eviction, emitting
    real KV events (reference mocker/kv_manager.rs:524)."""

    def __init__(
        self,
        args: MockEngineArgs,
        on_stored: Optional[Callable[[list[dict]], None]] = None,
        on_removed: Optional[Callable[[list[int]], None]] = None,
    ) -> None:
        self.args = args
        self.free_blocks = args.num_blocks
        # block_hash -> refcount; 0-ref blocks stay cached until evicted
        self.refs: dict[int, int] = {}
        self.lru: collections.OrderedDict[int, None] = collections.OrderedDict()
        self.on_stored = on_stored
        self.on_removed = on_removed

    @property
    def used_blocks(self) -> int:
        return self.args.num_blocks - self.free_blocks

    @property
    def usage(self) -> float:
        return self.used_blocks / max(1, self.args.num_blocks)

    @property
    def available_blocks(self) -> int:
        """Free + evictable (cached but unreferenced) blocks."""
        return self.free_blocks + sum(
            1 for h in self.lru if self.refs.get(h) == 0
        )

    def cached_prefix_blocks(self, hashes: list[int]) -> int:
        n = 0
        for h in hashes:
            if h in self.refs:
                n += 1
            else:
                break
        return n

    def _evict(self, need: int, protected: frozenset = frozenset()) -> bool:
        evicted: list[int] = []
        skipped: list[int] = []
        while need > 0 and self.lru:
            h, _ = self.lru.popitem(last=False)
            if h in protected:
                # cached block of the request being admitted — evicting it
                # would un-cache what we just counted as a prefix hit
                skipped.append(h)
                continue
            if self.refs.get(h, 1) == 0:
                del self.refs[h]
                self.free_blocks += 1
                evicted.append(h)
                need -= 1
        for h in skipped:
            self.lru[h] = None
        if evicted and self.on_removed:
            self.on_removed(evicted)
        return need <= 0

    def try_allocate(self, hashes: list[int], extra_unique: int) -> bool:
        """Acquire refs on all chain blocks (+unique partial blocks)."""
        new_hashes = [h for h in hashes if h not in self.refs]
        need = len(new_hashes) + extra_unique
        if need > self.free_blocks and not self._evict(
            need - self.free_blocks, frozenset(hashes)
        ):
            return False
        stored: list[dict] = []
        parent = 0
        for h in hashes:
            if h in self.refs:
                self.refs[h] += 1
                self.lru.pop(h, None)
            else:
                self.refs[h] = 1
                self.free_blocks -= 1
                stored.append({"block_hash": h, "parent_hash": parent})
            parent = h
        self.free_blocks -= extra_unique
        if stored and self.on_stored:
            self.on_stored(stored)
        return True

    def grow(self, new_blocks: list) -> bool:
        """A decode step completed new block(s) (TokenBlock instances)."""
        stored = []
        for b in new_blocks:
            h = b.block_hash
            if h in self.refs:
                self.refs[h] += 1
                self.lru.pop(h, None)
            else:
                if self.free_blocks <= 0 and not self._evict(1):
                    return False
                self.refs[h] = 1
                self.free_blocks -= 1
                stored.append({"block_hash": h, "parent_hash": b.parent_hash})
        if stored and self.on_stored:
            self.on_stored(stored)
        return True

    def release(self, hashes: list[int], unique: int) -> None:
        """Drop refs; 0-ref blocks become evictable (stay cached)."""
        for h in hashes:
            n = self.refs.get(h)
            if n is None:
                continue
            if n <= 1:
                self.refs[h] = 0
                self.lru[h] = None
                self.lru.move_to_end(h)
            else:
                self.refs[h] = n - 1
        self.free_blocks += unique


@dataclass
class _MockSeq:
    request: PreprocessedRequest
    context: Context
    out: asyncio.Queue
    hash_seq: TokenBlockSequence
    generated: int = 0
    prompt_len: int = 0  # original prompt length (< len(token_ids) on resume)
    acquired_hashes: list[int] = field(default_factory=list)
    unique_blocks: int = 1

    @property
    def prompt(self) -> list[int]:
        return self.request.token_ids[: self.prompt_len]


class MockEngine:
    """AsyncEngine-compatible: generate(request, context) -> LLMEngineOutput
    stream, same surface as JaxEngine/EchoEngine."""

    def __init__(
        self,
        args: Optional[MockEngineArgs] = None,
        on_blocks_stored: Optional[Callable[[list[dict]], None]] = None,
        on_blocks_removed: Optional[Callable[[list[int]], None]] = None,
    ) -> None:
        self.args = args or MockEngineArgs()
        self.cache = _SimKvCache(self.args, on_blocks_stored, on_blocks_removed)
        self.active: list[_MockSeq] = []
        self.waiting: collections.deque[_MockSeq] = collections.deque()
        self._loop_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self.generated_tokens = 0
        # cumulative UNCACHED prompt tokens actually prefilled; the routing
        # tests compare this (deterministic) rather than wall-clock TTFT
        self.prefilled_tokens = 0
        # lifeguard counters (same names the JaxEngine stats carry)
        self.deadline_exceeded = 0
        self.injected_aborts = 0

    # Hook properties matching JaxEngine's surface so worker hosting can
    # attach a KvEventPublisher uniformly (entrypoint/inputs.py).
    @property
    def on_blocks_stored(self):
        return self.cache.on_stored

    @on_blocks_stored.setter
    def on_blocks_stored(self, fn) -> None:
        self.cache.on_stored = fn

    @property
    def on_blocks_removed(self):
        return self.cache.on_removed

    @on_blocks_removed.setter
    def on_blocks_removed(self, fn) -> None:
        self.cache.on_removed = fn

    # ------------------------------------------------------------- public

    async def generate(
        self, request: PreprocessedRequest, context: Optional[Context] = None
    ) -> AsyncIterator[LLMEngineOutput]:
        ctx = context or Context()
        if ctx.expired() or ctx.ttft_expired():
            self.deadline_exceeded += 1
            yield LLMEngineOutput.final_error(
                ctx.id, "admission", "deadline expired before admission",
                "deadline_exceeded",
            )
            return
        # in-flight migration replay (see JaxEngine._Sequence): the tail of
        # token_ids past resume_prompt_len was already streamed by a dead
        # worker; counting it as generated keeps the deterministic token
        # cycle and the max_tokens budget identical to an unfaulted run
        prompt_len = len(request.token_ids)
        resume = int(request.extra.get("resume_prompt_len") or 0)
        if 0 < resume < prompt_len:
            prompt_len = resume
        seq = _MockSeq(
            request=request,
            context=ctx,
            out=asyncio.Queue(),
            prompt_len=prompt_len,
            generated=len(request.token_ids) - prompt_len,
            hash_seq=TokenBlockSequence(
                block_size=self.args.block_size,
                tokens=list(request.token_ids),
            ),
        )
        self.waiting.append(seq)
        self._wake.set()
        self._ensure_loop()
        try:
            while True:
                item = await seq.out.get()
                yield item
                if item.finish_reason is not None:
                    return
        finally:
            # consumer disconnected mid-stream: mark the request dead so the
            # sim loop releases its cache blocks instead of generating into
            # a queue nobody reads (mirrors JaxEngine.generate)
            ctx.kill()
            self._wake.set()

    def stats(self) -> dict:
        return {
            "active_slots": len(self.active),
            "total_slots": self.args.max_batch,
            "waiting": len(self.waiting),
            "used_blocks": self.cache.used_blocks,
            "total_blocks": self.args.num_blocks,
            "cache_usage": self.cache.usage,
            "deadline_exceeded": self.deadline_exceeded,
        }

    async def close(self) -> None:
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
            self._loop_task = None

    # -------------------------------------------------------------- sched

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.create_task(self._run())

    async def _sim_sleep(self, sim_s: float) -> None:
        await asyncio.sleep(sim_s / self.args.speedup_ratio)

    def _admit(self) -> float:
        """Watermark admission (scheduler.rs:197); returns prefill sim-cost."""
        cost = 0.0
        watermark_blocks = int(self.args.num_blocks * self.args.watermark)
        # reap abandoned requests before they consume sim capacity
        for seq in [s for s in self.waiting if s.context.is_killed()]:
            self.waiting.remove(seq)
            seq.out.put_nowait(LLMEngineOutput.final(FinishReason.CANCELLED))
        # shed queued requests past their deadline / TTFT budget
        for seq in [
            s for s in self.waiting
            if s.context.expired() or s.context.ttft_expired()
        ]:
            self.waiting.remove(seq)
            self.deadline_exceeded += 1
            seq.context.kill()
            seq.out.put_nowait(
                LLMEngineOutput.final_error(
                    seq.context.id, "queue",
                    "deadline exceeded while queued", "deadline_exceeded",
                )
            )
        while self.waiting and len(self.active) < self.args.max_batch:
            seq = self.waiting[0]
            hashes = [b.block_hash for b in seq.hash_seq.blocks]
            cached = self.cache.cached_prefix_blocks(hashes)
            if (
                self.cache.available_blocks - (len(hashes) - cached)
                < watermark_blocks
            ):
                break
            if not self.cache.try_allocate(hashes, extra_unique=1):
                break
            self.waiting.popleft()
            seq.acquired_hashes = list(hashes)
            self.active.append(seq)
            n_prefill = max(0, len(seq.request.token_ids)
                            - cached * self.args.block_size)
            self.prefilled_tokens += n_prefill
            cost += (
                self.args.prefill_linear_s * n_prefill
                + self.args.prefill_quadratic_s * n_prefill * n_prefill
            )
        return cost

    async def _run(self) -> None:
        while True:
            if not self.active and not self.waiting:
                self._wake.clear()
                await self._wake.wait()
            prefill_cost = self._admit()
            if prefill_cost:
                await self._sim_sleep(prefill_cost)
            if not self.active:
                # blocked: waiting head cannot be admitted yet
                if self.waiting:
                    await asyncio.sleep(0.001)
                continue
            # one decode iteration for the whole batch
            if faults.active():
                inj = faults.get_injector()
                if inj is not None:
                    await inj.on_dispatch()
            await self._sim_sleep(self.args.decode_per_token_s)
            # deadline expiry mid-generation: cancel + structured error
            for seq in [
                s for s in list(self.active) if s.context.expired()
            ]:
                self.deadline_exceeded += 1
                seq.context.kill()
                self.active.remove(seq)
                self.cache.release(seq.acquired_hashes, seq.unique_blocks)
                seq.out.put_nowait(
                    LLMEngineOutput.final_error(
                        seq.context.id, "decode",
                        "deadline exceeded mid-generation",
                        "deadline_exceeded",
                    )
                )
            for seq in list(self.active):
                self._step_seq(seq)

    def _abort_all(self, cause: str) -> None:
        """Injected crash (faults.abort_after_tokens): fail every live
        sequence with a structured error and release every cache ref —
        the simulated twin of a worker process dying mid-stream."""
        self.injected_aborts += 1
        for seq in list(self.waiting):
            self.waiting.remove(seq)
            seq.out.put_nowait(
                LLMEngineOutput.final_error(
                    seq.context.id, "queue", cause, "injected_fault"
                )
            )
        for seq in list(self.active):
            self.active.remove(seq)
            self.cache.release(seq.acquired_hashes, seq.unique_blocks)
            seq.out.put_nowait(
                LLMEngineOutput.final_error(
                    seq.context.id, "decode", cause, "injected_fault"
                )
            )

    def _step_seq(self, seq: _MockSeq) -> None:
        if seq not in self.active:
            # released mid-iteration (an injected abort earlier in this
            # batch step): stepping a zombie would re-acquire cache refs
            return
        if faults.active():
            inj = faults.get_injector()
            if inj is not None and inj.on_token():
                self._abort_all("injected engine fault (abort_after_tokens)")
                return
        # Deterministic fake token: cycle over the ORIGINAL prompt (on a
        # migration replay, token_ids carries already-emitted output too —
        # cycling over it would diverge from the unfaulted run)
        prompt = seq.prompt
        tok = prompt[seq.generated % max(1, len(prompt))]
        seq.generated += 1
        self.generated_tokens += 1
        prev_blocks = len(seq.hash_seq.blocks)
        seq.hash_seq.append(tok)
        new_blocks = seq.hash_seq.blocks[prev_blocks:]
        if new_blocks:
            if not self.cache.grow(new_blocks):
                self._preempt_for(seq)
                return
            seq.acquired_hashes.extend(b.block_hash for b in new_blocks)
        max_tokens = seq.request.stop.max_tokens or 64
        finished = seq.generated >= max_tokens or seq.context.is_stopped()
        reason = None
        if finished:
            reason = (
                FinishReason.CANCELLED
                if seq.context.is_stopped()
                else FinishReason.LENGTH
            )
        seq.out.put_nowait(
            LLMEngineOutput(
                token_ids=[tok],
                finish_reason=reason,
            )
        )
        if finished:
            self.active.remove(seq)
            self.cache.release(seq.acquired_hashes, seq.unique_blocks)

    def _preempt_for(self, seq: _MockSeq) -> None:
        if seq in self.active:
            self.active.remove(seq)
        self.cache.release(seq.acquired_hashes, seq.unique_blocks)
        seq.acquired_hashes = []
        self.waiting.appendleft(seq)
