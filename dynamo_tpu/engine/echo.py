"""Echo engines: deterministic fake engines for tests and pipeline bring-up.

Role-equivalent of lib/llm/src/engines.rs:66-128 (EchoEngineCore /
EchoEngineFull, ~100 tok/s paced by DYN_TOKEN_ECHO_DELAY_MS): echo_core
replays the prompt's token ids one by one; echo_full emits pre-detokenized
text (exercising the engines-that-detokenize path).
"""

from __future__ import annotations

import asyncio
import os
from typing import AsyncIterator, Optional

from dynamo_tpu.pipeline.context import Context
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.telemetry import trace as dtrace
from dynamo_tpu.testing import faults


def _delay_s() -> float:
    return float(os.environ.get("DYN_TOKEN_ECHO_DELAY_MS", "10")) / 1000.0


class EchoEngineCore:
    """Echoes prompt token ids back as generation output."""

    trace_proc: Optional[str] = None  # set by the worker host (run_endpoint)

    async def generate(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[LLMEngineOutput]:
        delay = _delay_s()
        # migration replay: the tail past resume_prompt_len was already
        # streamed by a previous worker — echo the ORIGINAL prompt and
        # resume the cycle where the dead worker stopped, so the stitched
        # stream is token-identical to an unfaulted run
        prompt = list(request.token_ids)
        count = 0
        resume = int(request.extra.get("resume_prompt_len") or 0)
        if 0 < resume < len(prompt):
            count = len(prompt) - resume
            prompt = prompt[:resume]
        limit = request.stop.max_tokens or len(prompt)
        with dtrace.span(
            "decode", ctx=context, proc=self.trace_proc,
            resumed_at=count or None,
        ) as sp:
            for tok in prompt[count:]:
                if faults.active():
                    # DYN_FAULT kill_after_tokens: the worker process dies
                    # exactly as a crashed decode worker would, mid-stream
                    inj = faults.get_injector()
                    if inj is not None:
                        inj.on_token()
                if context.is_stopped() or count >= limit:
                    break
                if context.expired():
                    context.kill()
                    sp.event("deadline_exceeded", phase="decode")
                    yield LLMEngineOutput.final_error(
                        context.id, "decode",
                        "deadline exceeded mid-generation",
                        "deadline_exceeded",
                    )
                    return
                await asyncio.sleep(delay)
                yield LLMEngineOutput(token_ids=[tok])
                count += 1
            sp.set(tokens=count)
        reason = (
            FinishReason.CANCELLED
            if context.is_killed()
            else (FinishReason.LENGTH if count >= limit else FinishReason.STOP)
        )
        yield LLMEngineOutput.final(reason)


class EchoEngineFull:
    """Echoes the prompt text back word by word (pre-detokenized path)."""

    def __init__(self, text_source_key: str = "echo_text") -> None:
        self.text_source_key = text_source_key

    async def generate(
        self, request: PreprocessedRequest, context: Context
    ) -> AsyncIterator[LLMEngineOutput]:
        delay = _delay_s()
        text = request.extra.get(self.text_source_key, "")
        words = text.split(" ") if text else [str(t) for t in request.token_ids]
        limit = request.stop.max_tokens or len(words)
        count = 0
        for i, w in enumerate(words):
            if context.is_stopped() or count >= limit:
                break
            await asyncio.sleep(delay)
            yield LLMEngineOutput(text=(w if i == 0 else " " + w))
            count += 1
        reason = (
            FinishReason.CANCELLED
            if context.is_killed()
            else (FinishReason.LENGTH if count >= limit else FinishReason.STOP)
        )
        yield LLMEngineOutput.final(reason)
