"""Stream -> unary aggregation: folds a stream of chunks into one response.

Role-equivalent of lib/llm/src/protocols/openai/chat_completions/aggregator.rs
(DeltaAggregator :32) and completions/aggregator.rs — used when the client
asked for a non-streaming response but the engine always streams.
"""

from __future__ import annotations

from typing import AsyncIterator, Optional

from dynamo_tpu.protocols.openai import (
    ChatChoice,
    ChatCompletionChunk,
    ChatCompletionResponse,
    ChatMessage,
    CompletionChoice,
    CompletionResponse,
)


class ChatDeltaAggregator:
    def __init__(self) -> None:
        self.id: str = ""
        self.model: str = ""
        self.created: int = 0
        self.usage: Optional[dict] = None
        self._choices: dict[int, dict] = {}

    def add(self, chunk: ChatCompletionChunk) -> None:
        self.id = chunk.id or self.id
        self.model = chunk.model or self.model
        self.created = chunk.created or self.created
        if chunk.usage:
            self.usage = chunk.usage
        for c in chunk.choices:
            slot = self._choices.setdefault(
                c.index,
                {
                    "role": None, "content": [], "finish_reason": None,
                    "tool_calls": [], "logprobs": [],
                },
            )
            if c.delta.role:
                slot["role"] = c.delta.role
            if c.delta.content:
                slot["content"].append(c.delta.content)
            if c.delta.tool_calls:
                slot["tool_calls"].extend(c.delta.tool_calls)
            if c.logprobs and c.logprobs.get("content"):
                slot["logprobs"].extend(c.logprobs["content"])
            if c.finish_reason:
                slot["finish_reason"] = c.finish_reason

    def finish(self) -> ChatCompletionResponse:
        choices = [
            ChatChoice(
                index=i,
                message=ChatMessage(
                    role=slot["role"] or "assistant",
                    content="".join(slot["content"]),
                    tool_calls=slot["tool_calls"] or None,
                ),
                finish_reason=slot["finish_reason"],
                logprobs={"content": slot["logprobs"]}
                if slot["logprobs"]
                else None,
            )
            for i, slot in sorted(self._choices.items())
        ]
        kwargs = dict(id=self.id, model=self.model, choices=choices, usage=self.usage)
        if self.created:
            kwargs["created"] = self.created
        return ChatCompletionResponse(**kwargs)

    @classmethod
    async def fold(
        cls, chunks: AsyncIterator[ChatCompletionChunk]
    ) -> ChatCompletionResponse:
        agg = cls()
        async for chunk in chunks:
            agg.add(chunk)
        return agg.finish()


class CompletionAggregator:
    def __init__(self) -> None:
        self.id = ""
        self.model = ""
        self.usage: Optional[dict] = None
        self._choices: dict[int, dict] = {}

    def add(self, chunk: CompletionResponse) -> None:
        self.id = chunk.id or self.id
        self.model = chunk.model or self.model
        if chunk.usage:
            self.usage = chunk.usage
        for c in chunk.choices:
            slot = self._choices.setdefault(
                c.index, {"text": [], "finish_reason": None, "logprobs": None}
            )
            if c.text:
                slot["text"].append(c.text)
            if c.logprobs:
                lp = slot["logprobs"] or {
                    "tokens": [], "token_logprobs": [],
                    "top_logprobs": [], "text_offset": [],
                }
                for key in (
                    "tokens", "token_logprobs", "top_logprobs", "text_offset"
                ):
                    lp[key].extend(c.logprobs.get(key, []))
                slot["logprobs"] = lp
            if c.finish_reason:
                slot["finish_reason"] = c.finish_reason

    def finish(self) -> CompletionResponse:
        return CompletionResponse(
            id=self.id,
            model=self.model,
            choices=[
                CompletionChoice(
                    index=i,
                    text="".join(slot["text"]),
                    finish_reason=slot["finish_reason"],
                    logprobs=slot["logprobs"],
                )
                for i, slot in sorted(self._choices.items())
            ],
            usage=self.usage,
        )
