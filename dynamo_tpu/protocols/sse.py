"""Server-sent-events codec.

Role-equivalent of lib/llm/src/protocols/codec.rs (SseLineCodec :53) — both
directions: encoding Annotated/model chunks as SSE for HTTP responses, and
parsing SSE streams (used by clients and tests).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Optional

DONE_SENTINEL = "[DONE]"


@dataclass
class SseEvent:
    data: Optional[str] = None
    event: Optional[str] = None
    comments: list[str] = field(default_factory=list)
    id: Optional[str] = None

    def encode(self) -> str:
        lines: list[str] = []
        for c in self.comments:
            lines.append(f": {c}")
        if self.event is not None:
            lines.append(f"event: {self.event}")
        if self.id is not None:
            lines.append(f"id: {self.id}")
        if self.data is not None:
            for chunk in self.data.split("\n"):
                lines.append(f"data: {chunk}")
        return "\n".join(lines) + "\n\n"

    def is_done(self) -> bool:
        return self.data is not None and self.data.strip() == DONE_SENTINEL

    def json(self) -> Any:
        return json.loads(self.data) if self.data else None


def encode_json_event(obj: Any, event: Optional[str] = None) -> str:
    return SseEvent(data=json.dumps(obj, separators=(",", ":")), event=event).encode()


def encode_done() -> str:
    return SseEvent(data=DONE_SENTINEL).encode()


class SseParser:
    """Incremental SSE parser: feed text chunks, yields complete SseEvents."""

    def __init__(self) -> None:
        self._buffer = ""

    def feed(self, text: str) -> list[SseEvent]:
        self._buffer += text
        events: list[SseEvent] = []
        while "\n\n" in self._buffer:
            raw, self._buffer = self._buffer.split("\n\n", 1)
            ev = self._parse_block(raw)
            if ev is not None:
                events.append(ev)
        return events

    @staticmethod
    def _parse_block(block: str) -> Optional[SseEvent]:
        ev = SseEvent()
        data_lines: list[str] = []
        seen = False
        for line in block.split("\n"):
            if not line.strip():
                continue
            seen = True
            if line.startswith(":"):
                ev.comments.append(line[1:].strip())
            elif line.startswith("event:"):
                ev.event = line[len("event:") :].strip()
            elif line.startswith("id:"):
                ev.id = line[len("id:") :].strip()
            elif line.startswith("data:"):
                data_lines.append(line[len("data:") :].lstrip())
        if not seen:
            return None
        if data_lines:
            ev.data = "\n".join(data_lines)
        return ev


async def parse_sse_stream(
    chunks: AsyncIterator[bytes],
) -> AsyncIterator[SseEvent]:
    parser = SseParser()
    async for chunk in chunks:
        for ev in parser.feed(chunk.decode("utf-8", errors="replace")):
            yield ev
