"""LLM wire protocols: OpenAI-compatible API types, internal engine types,
SSE codec, and stream aggregators.

Role-equivalent of the reference's lib/llm/src/protocols tree."""

from dynamo_tpu.protocols.common import (  # noqa: F401
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
