"""OpenAI-compatible API types (chat completions, completions, embeddings)
plus the `ext` extension block.

Role-equivalent of lib/llm/src/protocols/openai/* — request/response models
with validation, delta (streaming chunk) types, and the nvext-style extension
(openai/nvext.rs:28: annotations, ignore_eos, greedy). We accept the
extension under either key "ext" or "nvext" for client compatibility.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, model_validator


class Ext(BaseModel):
    """Extension block: out-of-band annotations + sampling overrides."""

    model_config = ConfigDict(extra="allow")
    annotations: list[str] = Field(default_factory=list)
    ignore_eos: bool = False
    greedy: bool = False
    # request lifeguard budgets (ms, relative to arrival): the whole
    # request must finish within timeout_ms, and the first token must be
    # produced within ttft_timeout_ms — else the request is cancelled
    # end-to-end and a structured `deadline_exceeded` error is streamed.
    # Unset fields fall back to DYN_DEFAULT_DEADLINE_MS / unbounded.
    timeout_ms: Optional[float] = Field(default=None, gt=0)
    ttft_timeout_ms: Optional[float] = Field(default=None, gt=0)
    # QoS class: interactive | standard | bulk (qos.py normalizes spelling
    # aliases, including the 0/1/2 rank shorthand). The x-dyn-priority
    # header beats this; DYN_PRIORITY_DEFAULT supplies the per-model
    # default when neither is present.
    priority: Optional[Union[str, int]] = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: str
    content: Optional[Union[str, list[dict[str, Any]]]] = None
    name: Optional[str] = None
    tool_calls: Optional[list[dict[str, Any]]] = None
    tool_call_id: Optional[str] = None

    def text_content(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if isinstance(self.content, list):
            return "".join(
                part.get("text", "")
                for part in self.content
                if part.get("type") == "text"
            )
        return ""


class _CommonSampling(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    temperature: Optional[float] = Field(default=None, ge=0.0, le=2.0)
    top_p: Optional[float] = Field(default=None, gt=0.0, le=1.0)
    top_k: Optional[int] = Field(default=None, ge=0)
    frequency_penalty: Optional[float] = Field(default=None, ge=-2.0, le=2.0)
    presence_penalty: Optional[float] = Field(default=None, ge=-2.0, le=2.0)
    repetition_penalty: Optional[float] = Field(default=None, gt=0.0, le=2.0)
    min_tokens: Optional[int] = Field(default=None, ge=0)
    seed: Optional[int] = None
    n: int = Field(default=1, ge=1, le=16)
    stream: bool = False
    stream_options: Optional[dict[str, Any]] = None
    stop: Optional[Union[str, list[str]]] = None
    logprobs: Optional[Union[bool, int]] = None
    top_logprobs: Optional[int] = Field(default=None, ge=0, le=20)
    user: Optional[str] = None
    ext: Optional[Ext] = None

    @model_validator(mode="before")
    @classmethod
    def _accept_nvext(cls, data: Any) -> Any:
        if isinstance(data, dict) and "nvext" in data and "ext" not in data:
            data = dict(data)
            data["ext"] = data.pop("nvext")
        return data

    def stop_list(self) -> list[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class ChatCompletionRequest(_CommonSampling):
    messages: list[ChatMessage]
    max_tokens: Optional[int] = Field(default=None, ge=1)
    max_completion_tokens: Optional[int] = Field(default=None, ge=1)
    tools: Optional[list[dict[str, Any]]] = None
    tool_choice: Optional[Union[str, dict[str, Any]]] = None
    response_format: Optional[dict[str, Any]] = None

    def output_limit(self) -> Optional[int]:
        return self.max_completion_tokens or self.max_tokens


class CompletionRequest(_CommonSampling):
    prompt: Union[str, list[str], list[int], list[list[int]]]
    max_tokens: Optional[int] = Field(default=16, ge=1)
    echo: bool = False

    def output_limit(self) -> Optional[int]:
        return self.max_tokens


class EmbeddingRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    input: Union[str, list[str], list[int], list[list[int]]]
    encoding_format: str = "float"


# --------------------------------------------------------------- responses


def _now() -> int:
    return int(time.time())


def gen_request_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


class ChoiceDelta(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: Optional[str] = None
    content: Optional[str] = None
    tool_calls: Optional[list[dict[str, Any]]] = None


class StreamChoice(BaseModel):
    index: int = 0
    delta: ChoiceDelta = Field(default_factory=ChoiceDelta)
    finish_reason: Optional[str] = None
    logprobs: Optional[dict[str, Any]] = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=_now)
    model: str = ""
    choices: list[StreamChoice] = Field(default_factory=list)
    usage: Optional[dict[str, Any]] = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage = Field(default_factory=lambda: ChatMessage(role="assistant"))
    finish_reason: Optional[str] = None
    logprobs: Optional[dict[str, Any]] = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=_now)
    model: str = ""
    choices: list[ChatChoice] = Field(default_factory=list)
    usage: Optional[dict[str, Any]] = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[dict[str, Any]] = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=_now)
    model: str = ""
    choices: list[CompletionChoice] = Field(default_factory=list)
    usage: Optional[dict[str, Any]] = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=_now)
    owned_by: str = "dynamo_tpu"


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: list[ModelInfo] = Field(default_factory=list)


def usage_dict(prompt_tokens: int, completion_tokens: int) -> dict[str, int]:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }
