"""Engine-agnostic internal request/response types.

Role-equivalent of lib/llm/src/protocols/common (PreprocessedRequest,
LLMEngineOutput at common/llm_backend.rs:184, SamplingOptionsProvider /
StopConditionsProvider). These are the types that flow between the
preprocessor, the router, and the engine — all token-space, no OpenAI shapes.

Everything is a plain dict-convertible dataclass: these cross process
boundaries as msgpack maps on the fabric bus.
"""

from __future__ import annotations

import enum
from dataclasses import asdict, dataclass, field
from typing import Any, Optional


class FinishReason(str, enum.Enum):
    STOP = "stop"
    LENGTH = "length"
    EOS = "eos"
    STOP_SEQUENCE = "stop_sequence"
    CANCELLED = "cancelled"
    ERROR = "error"

    def as_openai(self) -> str:
        if self in (FinishReason.EOS, FinishReason.STOP_SEQUENCE):
            return "stop"
        if self is FinishReason.LENGTH:
            return "length"
        return self.value


@dataclass
class SamplingOptions:
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    n: int = 1
    greedy: bool = False
    # logprob surface (openai `logprobs`/`top_logprobs`)
    logprobs: bool = False
    top_logprobs: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {k: v for k, v in asdict(self).items() if v is not None}

    @classmethod
    def from_dict(cls, d: Optional[dict[str, Any]]) -> "SamplingOptions":
        if not d:
            return cls()
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclass
class StopConditions:
    max_tokens: Optional[int] = None
    stop: list[str] = field(default_factory=list)  # visible stop strings
    stop_token_ids_hidden: list[int] = field(default_factory=list)
    min_tokens: Optional[int] = None
    ignore_eos: bool = False

    def to_dict(self) -> dict[str, Any]:
        return {k: v for k, v in asdict(self).items() if v not in (None, [])}

    @classmethod
    def from_dict(cls, d: Optional[dict[str, Any]]) -> "StopConditions":
        if not d:
            return cls()
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclass
class PreprocessedRequest:
    """The tokenized request handed to routers and engines."""

    token_ids: list[int]
    model: str = ""
    sampling: SamplingOptions = field(default_factory=SamplingOptions)
    stop: StopConditions = field(default_factory=StopConditions)
    eos_token_ids: list[int] = field(default_factory=list)
    annotations: list[str] = field(default_factory=list)  # requested annotations
    # router hints
    estimated_prefix_hit_blocks: int = 0
    extra: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "token_ids": self.token_ids,
            "model": self.model,
            "sampling": self.sampling.to_dict(),
            "stop": self.stop.to_dict(),
            "eos_token_ids": self.eos_token_ids,
            "annotations": self.annotations,
            "estimated_prefix_hit_blocks": self.estimated_prefix_hit_blocks,
            "extra": self.extra,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d.get("token_ids", [])),
            model=d.get("model", ""),
            sampling=SamplingOptions.from_dict(d.get("sampling")),
            stop=StopConditions.from_dict(d.get("stop")),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            annotations=list(d.get("annotations", [])),
            estimated_prefix_hit_blocks=d.get("estimated_prefix_hit_blocks", 0),
            extra=d.get("extra", {}) or {},
        )


@dataclass
class LLMEngineOutput:
    """One streamed engine step result (a delta, token-space)."""

    token_ids: list[int] = field(default_factory=list)
    text: Optional[str] = None  # engines that detokenize themselves
    cum_log_probs: Optional[float] = None
    finish_reason: Optional[FinishReason] = None
    index: int = 0  # choice index for n>1
    # per-token logprob of each id in token_ids (when requested)
    log_probs: Optional[list[float]] = None
    # per-token top-K alternatives: [[(token_id, logprob), ...], ...]
    top_logprobs: Optional[list[list[list[float]]]] = None
    # structured failure payload on ERROR finals: {"request_id", "phase",
    # "cause", "code"} — reaches the SSE stream as a typed error event
    error: Optional[dict[str, Any]] = None
    # completed telemetry spans riding the FINAL frame back to the caller
    # (worker -> frontend trace assembly; stripped before the HTTP layer)
    trace: Optional[list] = None
    # worker-side decision records riding the FINAL frame next to `trace`
    # (worker -> frontend provenance assembly; same lifecycle)
    decisions: Optional[list] = None

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"token_ids": self.token_ids, "index": self.index}
        if self.text is not None:
            out["text"] = self.text
        if self.cum_log_probs is not None:
            out["cum_log_probs"] = self.cum_log_probs
        if self.finish_reason is not None:
            out["finish_reason"] = self.finish_reason.value
        if self.log_probs is not None:
            out["log_probs"] = self.log_probs
        if self.top_logprobs is not None:
            out["top_logprobs"] = self.top_logprobs
        if self.error is not None:
            out["error"] = self.error
        if self.trace is not None:
            out["trace"] = self.trace
        if self.decisions is not None:
            out["decisions"] = self.decisions
        return out

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "LLMEngineOutput":
        fr = d.get("finish_reason")
        return cls(
            token_ids=list(d.get("token_ids", [])),
            text=d.get("text"),
            cum_log_probs=d.get("cum_log_probs"),
            finish_reason=FinishReason(fr) if fr else None,
            index=d.get("index", 0),
            log_probs=d.get("log_probs"),
            top_logprobs=d.get("top_logprobs"),
            error=d.get("error"),
            trace=d.get("trace"),
            decisions=d.get("decisions"),
        )

    @classmethod
    def final(cls, reason: FinishReason) -> "LLMEngineOutput":
        return cls(finish_reason=reason)

    @classmethod
    def final_error(
        cls,
        request_id: str,
        phase: str,
        cause: str,
        code: str = "internal_error",
    ) -> "LLMEngineOutput":
        """An ERROR final carrying a structured, per-sequence failure
        payload (request id, pipeline phase, cause, machine-readable code)
        instead of a bare finish reason."""
        return cls(
            finish_reason=FinishReason.ERROR,
            error={
                "request_id": request_id,
                "phase": phase,
                "cause": cause,
                "code": code,
            },
        )
