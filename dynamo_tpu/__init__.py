"""dynamo_tpu — a TPU-native distributed LLM inference-serving framework.

Provides the serving fabric (discovery, routing, disaggregation, KV-cache
management, autoscaling) of a Dynamo-class system plus a native JAX/XLA/pallas
engine with first-class TP/PP/EP sharding over TPU meshes.

Reference capability map: see SURVEY.md at the repo root. The reference system
(NVIDIA Dynamo, mounted read-only) is Rust/CUDA; this package is a ground-up
TPU-first redesign, not a port.
"""

__version__ = "0.1.0"

from dynamo_tpu.runtime.cancellation import CancellationToken  # noqa: F401
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: F401
