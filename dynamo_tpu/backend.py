"""Backend operator: incremental detokenization + stop-condition handling on
the engine's token-delta stream.

Role-equivalent of lib/llm/src/backend.rs (Backend :67, Decoder :278,
SeqResult::step :400): engines emit token ids; this operator turns them into
text deltas, detects visible stop strings across chunk boundaries (holding
back — "jailing" — text that might be the prefix of a stop sequence until it
is disambiguated), recognizes hidden eos tokens, and enforces max_tokens.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    StopConditions,
)
from dynamo_tpu.pipeline.nodes import Operator as PipelineOperator
from dynamo_tpu.tokenizer import TokenizerWrapper


@dataclass
class StepResult:
    text: str = ""
    finish_reason: Optional[FinishReason] = None
    tokens_emitted: int = 0
    # OpenAI chat logprobs content entries for tokens emitted this step
    # ({"token", "logprob", "bytes", "top_logprobs"}), when requested
    logprobs: Optional[list[dict]] = None
    # structured failure payload riding an ERROR final (LLMEngineOutput.error)
    error: Optional[dict] = None


class SequenceDecoder:
    """Per-request decoder state (one choice index)."""

    def __init__(
        self,
        tokenizer: TokenizerWrapper,
        stop: StopConditions,
        eos_token_ids: list[int],
    ) -> None:
        self._tokenizer = tokenizer
        self._stream = tokenizer.decode_stream()
        self._stop = stop
        self._eos = set(eos_token_ids) | set(stop.stop_token_ids_hidden)
        self._stop_seqs = list(stop.stop)
        self._max_hold = max((len(s) for s in self._stop_seqs), default=0)
        self._jail = ""  # held-back text possibly prefixing a stop sequence
        self._emitted_tokens = 0
        self.finished: Optional[FinishReason] = None

    def _scan_stop(self, text: str) -> tuple[str, bool]:
        """Returns (releasable_text, hit). Keeps a possible stop-seq prefix
        jailed in self._jail."""
        if not self._stop_seqs:
            return text, False
        buf = self._jail + text
        for seq in self._stop_seqs:
            idx = buf.find(seq)
            if idx != -1:
                self._jail = ""
                return buf[:idx], True  # visible text before the stop string
        # keep the longest tail that could still grow into a stop sequence
        hold = 0
        for seq in self._stop_seqs:
            for k in range(min(len(seq) - 1, len(buf)), 0, -1):
                if buf.endswith(seq[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            self._jail = buf[-hold:]
            return buf[:-hold], False
        self._jail = ""
        return buf, False

    def step(self, output: LLMEngineOutput) -> StepResult:
        """Fold one engine delta; returns text to emit + finish state."""
        if self.finished is not None:
            return StepResult(finish_reason=self.finished)
        result = StepResult()
        if output.text is not None:
            # engine already detokenized (e.g. echo_full)
            pieces = output.text
            released, hit = self._scan_stop(pieces)
            result.text += released
            self._emitted_tokens += max(len(output.token_ids), 1)
            result.tokens_emitted += max(len(output.token_ids), 1)
            if hit:
                self.finished = FinishReason.STOP_SEQUENCE
        else:
            for j, tok in enumerate(output.token_ids):
                if not self._stop.ignore_eos and tok in self._eos:
                    self.finished = FinishReason.EOS
                    break
                piece = self._stream.step(tok)
                self._emitted_tokens += 1
                result.tokens_emitted += 1
                if output.log_probs is not None and j < len(output.log_probs):
                    entry = self._logprob_entry(
                        tok,
                        piece,
                        output.log_probs[j],
                        output.top_logprobs[j]
                        if output.top_logprobs and j < len(output.top_logprobs)
                        else None,
                    )
                    result.logprobs = (result.logprobs or []) + [entry]
                if piece:
                    released, hit = self._scan_stop(piece)
                    result.text += released
                    if hit:
                        self.finished = FinishReason.STOP_SEQUENCE
                        break
                if (
                    self._stop.max_tokens is not None
                    and self._emitted_tokens >= self._stop.max_tokens
                ):
                    self.finished = FinishReason.LENGTH
                    break
        if self.finished is None and output.finish_reason is not None:
            self.finished = output.finish_reason
        result.finish_reason = self.finished
        if output.error is not None:
            result.error = output.error
        return result

    def _logprob_entry(
        self,
        token_id: int,
        piece: str,
        logprob: float,
        top: Optional[list],
    ) -> dict:
        """One OpenAI chat-logprobs content entry (openai.rs logprobs
        surface). `piece` may be '' when the byte-level stream is holding
        back an incomplete codepoint — fall back to a solo decode."""
        text = piece or self._decode_one(token_id)
        entry: dict = {
            "token": text,
            "logprob": float(logprob),
            "bytes": list(text.encode("utf-8")),
        }
        if top:
            entry["top_logprobs"] = [
                {
                    "token": self._decode_one(int(tid)),
                    "logprob": float(lp),
                    "bytes": list(self._decode_one(int(tid)).encode("utf-8")),
                }
                for tid, lp in top
            ]
        return entry

    def _decode_one(self, token_id: int) -> str:
        try:
            return self._tokenizer.decode([token_id], skip_special_tokens=False)
        except Exception:  # noqa: BLE001 — display-only fallback
            return f"<{token_id}>"

    @property
    def emitted_tokens(self) -> int:
        return self._emitted_tokens


class Backend:
    """Factory wiring SequenceDecoders per request/choice."""

    def __init__(self, tokenizer: TokenizerWrapper) -> None:
        self.tokenizer = tokenizer

    def decoder(
        self, stop: StopConditions, eos_token_ids: list[int]
    ) -> SequenceDecoder:
        return SequenceDecoder(self.tokenizer, stop, eos_token_ids)


class DetokenizeOperator(PipelineOperator):
    """The backend node of the reference's per-model chain
    (lib/llm/src/backend.rs into_operator; linked at
    discovery/watcher.rs:205): forward passes the PreprocessedRequest
    through untouched; backward folds each LLMEngineOutput delta through
    a per-request SequenceDecoder (incremental detokenize, stop-sequence
    jail, EOS/length finish), yielding StepResults upstream."""

    def __init__(self, backend: Backend) -> None:
        self._backend = backend

    async def generate(self, request, ctx, next):
        from dynamo_tpu.telemetry import trace as dtrace

        decoder = self._backend.decoder(request.stop, request.eos_token_ids)
        agen = next.generate(request, ctx)
        try:
            async for out in agen:
                step = decoder.step(out)
                if step.finish_reason is not None:
                    if (
                        dtrace.enabled()
                        and out.finish_reason is None
                        and step.finish_reason is FinishReason.LENGTH
                    ):
                        # max_tokens counted HERE, one frame before the
                        # engine's own LENGTH final — with tracing on,
                        # drain briefly toward that final so the worker's
                        # completed spans (they ride it) are still
                        # consumed. Bounded: engines enforce max_tokens
                        # themselves, so the final is already in flight;
                        # a stall never exceeds the timeout. Zero
                        # behavior change with DYN_TRACE=0.
                        await _drain_for_final(agen)
                    yield step
                    return
                yield step
        finally:
            # deterministic teardown of the downstream chain (engine or
            # RemoteEngine generator): GC-deferred asyncgen finalization
            # would leave the worker stream open and drop any span whose
            # `with` is still suspended at a yield
            with contextlib.suppress(Exception):
                await agen.aclose()


async def _drain_for_final(agen, frames: int = 4, timeout_s: float = 0.25):
    import asyncio

    with contextlib.suppress(
        StopAsyncIteration, asyncio.TimeoutError, Exception
    ):
        for _ in range(frames):
            out = await asyncio.wait_for(agen.__anext__(), timeout_s)
            if out.finish_reason is not None:
                return
