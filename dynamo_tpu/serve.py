"""`python -m dynamo_tpu.serve <graph>` — launch a serve graph supervised.

Role-equivalent of the reference's `dynamo serve graphs.disagg:Frontend`
(deploy/sdk/src/dynamo/sdk/cli/serving.py:152): one command starts the
fabric control plane (unless DYN_FABRIC_ADDR points at one), then every
@service of the graph as supervised OS processes — dependencies first,
crash ⇒ restart with backoff, SIGINT/SIGTERM ⇒ graceful teardown.

    python -m dynamo_tpu.serve dynamo_tpu.graphs.agg \
        --env DYN_HTTP_PORT=8080 --replicas Worker=4
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import socket
from typing import Optional

from dynamo_tpu.runtime.config import default_jax_cache_dir
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.sdk import Supervisor, load_graph

logger = get_logger("dynamo_tpu.serve")


def _drain_timeout_s() -> float:
    """Graceful-drain budget for SIGTERM teardown (DYN_DRAIN_TIMEOUT_S)."""
    return float(os.environ.get("DYN_DRAIN_TIMEOUT_S", "10"))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def _wait_port(host: str, port: int, timeout: float = 10.0) -> None:
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        try:
            _, w = await asyncio.open_connection(host, port)
            w.close()
            await w.wait_closed()
            return
        except OSError:
            await asyncio.sleep(0.1)
    raise TimeoutError(f"fabric server not reachable on {host}:{port}")


async def serve_graph(
    graph_module: str,
    *,
    extra_env: Optional[dict[str, str]] = None,
    replica_overrides: Optional[dict[str, int]] = None,
    fabric_addr: Optional[str] = None,
    only: Optional[set[str]] = None,
) -> Supervisor:
    """Start the graph; returns the running Supervisor (also the FT-test
    entry point — tests kill members and assert recovery)."""
    if not graph_module.startswith("dynamo_tpu.") and "." not in graph_module:
        graph_module = f"dynamo_tpu.graphs.{graph_module}"
    sup = Supervisor()
    addr = fabric_addr or os.environ.get("DYN_FABRIC_ADDR")
    if not addr:
        port = _free_port()
        fabric_proc = sup.add_python(
            "fabric", "dynamo_tpu.fabric.server", "--port", str(port),
            max_restarts=10,
        )
        fabric_proc.stop_last = True  # services deregister before it dies
        addr = f"127.0.0.1:{port}"
    specs = load_graph(graph_module)
    if only:
        # one service of the graph per process — how the k8s operator
        # deploys graphs (each spec.services entry is its own Deployment)
        unknown = only - {s.name for s in specs}
        if unknown:
            raise SystemExit(
                f"--only {sorted(unknown)}: not in graph "
                f"{[s.name for s in specs]}"
            )
        specs = [s for s in specs if s.name in only]
    logger.info(
        "graph %s: %s (fabric %s)",
        graph_module, [s.name for s in specs], addr,
    )
    await sup.start_all()  # fabric first, so children can connect
    # addr may list an HA pair ("h1:p1,h2:p2"); any reachable member is
    # enough to proceed (the client finds the primary itself)
    last_err: Optional[Exception] = None
    for member in addr.split(","):
        host, _, port_s = member.strip().partition(":")
        try:
            await _wait_port(host, int(port_s))
            break
        except TimeoutError as e:
            last_err = e
    else:
        raise last_err or TimeoutError(f"no fabric member reachable: {addr}")
    for spec in specs:
        n = (replica_overrides or {}).get(spec.name, spec.replicas)
        for r in range(n):
            sup.add_python(
                f"{spec.name}-{r}",
                "dynamo_tpu.sdk.runner",
                spec.target,
                env={
                    "DYN_FABRIC_ADDR": addr,
                    # every jax-running service shares one persistent XLA
                    # compile cache across restarts (DYN_JAX_CACHE_DIR
                    # overrides, "off" disables) — a respawned worker
                    # skips the ~46.6 s cold compile of its program set
                    "DYN_JAX_CACHE_DIR": os.environ.get(
                        "DYN_JAX_CACHE_DIR", default_jax_cache_dir()
                    ),
                    **spec.env,
                    **(extra_env or {}),
                },
            )
    await sup.start_all()
    return sup


def main(argv: Optional[list[str]] = None) -> None:
    parser = argparse.ArgumentParser(prog="dynamo_tpu.serve")
    parser.add_argument("graph", help="graph module (e.g. dynamo_tpu.graphs.agg)")
    parser.add_argument(
        "--env", action="append", default=[], metavar="KEY=VAL",
        help="extra env for every service process",
    )
    parser.add_argument(
        "--replicas", action="append", default=[], metavar="NAME=N",
        help="override a service's replica count",
    )
    parser.add_argument("--fabric-addr", default=None)
    parser.add_argument(
        "--only", action="append", default=[], metavar="NAME",
        help="launch only these graph services (repeatable; the k8s "
        "operator runs one service per Deployment this way)",
    )
    args = parser.parse_args(argv)
    extra_env = dict(kv.split("=", 1) for kv in args.env)
    replicas = {
        k: int(v) for k, v in (kv.split("=", 1) for kv in args.replicas)
    }

    async def amain() -> None:
        sup = await serve_graph(
            args.graph,
            extra_env=extra_env,
            replica_overrides=replicas,
            fabric_addr=args.fabric_addr,
            only=set(args.only) or None,
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        logger.info("stopping graph (drain %ss)", _drain_timeout_s())
        # SIGTERM reaches each service's runner, which drains (stop
        # admission -> finish in-flight -> deregister) before exiting; the
        # supervisor's SIGKILL deadline leaves headroom for that drain
        await sup.stop_all(timeout=_drain_timeout_s() + 5.0)

    asyncio.run(amain())


if __name__ == "__main__":
    main()
