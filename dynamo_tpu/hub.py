"""Model resolution: name/path -> servable local path.

Role-equivalent of lib/llm/src/hub.rs:105 (from_hf): accept a local dir, a
.gguf file, or a HuggingFace repo id. Repo ids resolve through the standard
HF cache layout (models--org--name/snapshots/...); actual downloading is
GATED (DYN_ALLOW_DOWNLOAD=1 + huggingface_hub importable) because serving
fleets are commonly egress-less — the error message says exactly what to
pre-stage where.
"""

from __future__ import annotations

import os
from typing import Optional

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger("dynamo_tpu.hub")


def _cache_roots() -> list[str]:
    roots = []
    if os.environ.get("DYN_MODEL_CACHE"):
        roots.append(os.environ["DYN_MODEL_CACHE"])
    hf_home = os.environ.get("HF_HOME")
    if hf_home:
        roots.append(os.path.join(hf_home, "hub"))
    roots.append(os.path.expanduser("~/.cache/huggingface/hub"))
    return roots


def _find_in_cache(repo_id: str) -> Optional[str]:
    folder = "models--" + repo_id.replace("/", "--")
    for root in _cache_roots():
        snaps = os.path.join(root, folder, "snapshots")
        if not os.path.isdir(snaps):
            continue
        revs = sorted(
            (os.path.join(snaps, d) for d in os.listdir(snaps)),
            key=os.path.getmtime,
            reverse=True,
        )
        for rev in revs:
            if os.path.exists(os.path.join(rev, "config.json")) or any(
                f.endswith(".gguf") for f in os.listdir(rev)
            ):
                return rev
    return None


def resolve_model(name_or_path: str) -> str:
    """Local dir / .gguf file as-is; else HF-cache lookup; else a gated
    download; else an actionable error."""
    if os.path.isdir(name_or_path):
        return name_or_path
    if os.path.isfile(name_or_path) and name_or_path.endswith(".gguf"):
        return name_or_path
    cached = _find_in_cache(name_or_path)
    if cached:
        logger.info("resolved %s -> %s (hf cache)", name_or_path, cached)
        return cached
    if os.environ.get("DYN_ALLOW_DOWNLOAD") == "1":
        try:
            from huggingface_hub import snapshot_download  # type: ignore

            path = snapshot_download(name_or_path)
            logger.info("downloaded %s -> %s", name_or_path, path)
            return path
        except ImportError:
            raise FileNotFoundError(
                f"model {name_or_path!r}: DYN_ALLOW_DOWNLOAD=1 but "
                "huggingface_hub is not installed"
            ) from None
    raise FileNotFoundError(
        f"model {name_or_path!r} not found: not a local dir/.gguf, not in "
        f"the HF cache ({', '.join(_cache_roots())}). Pre-stage the model "
        "(huggingface-cli download on a connected host, or set "
        "DYN_MODEL_CACHE), or set DYN_ALLOW_DOWNLOAD=1 where egress exists."
    )
