"""SentencePiece tokenizer: native .model reader + encoder/decoder.

Role-equivalent of lib/llm/src/tokenizers/sp.rs (the reference wraps the
SentencePiece C++ library; this image has neither it nor protobuf, so the
.model file — a serialized ModelProto — is parsed directly off the
protobuf wire format, and encoding is implemented for both model types:

  * UNIGRAM — Viterbi segmentation maximizing the summed piece
    log-probabilities (the algorithm SentencePiece itself uses at
    inference);
  * BPE — iterative best-scored adjacent merges from characters, which is
    SentencePiece's BPE encode (scores are merge priorities).

Whitespace handling follows NormalizerSpec: escape_whitespaces maps
' ' -> '▁' (U+2581), add_dummy_prefix prepends one. Characters with no
piece coverage fall back to byte pieces ('<0xNN>') when the vocab has
them, else the unk id. The resulting SentencePieceTokenizer duck-types
the surface TokenizerWrapper needs (encode/decode/token_to_id/
get_vocab_size), so `TokenizerWrapper.from_model_dir` serves model dirs
that ship only tokenizer.model.
"""

from __future__ import annotations

import os
import unicodedata
from dataclasses import dataclass, field
from typing import Optional, Sequence

SPACE_PIECE = "▁"  # ▁

# SentencePiece ModelProto piece types
_NORMAL, _UNKNOWN, _CONTROL, _USER_DEFINED, _UNUSED, _BYTE = 1, 2, 3, 4, 5, 6


# ------------------------------------------------------- protobuf wire


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = buf[i]
        val |= (b & 0x7F) << shift
        i += 1
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf: bytes):
    """Iterate (field_number, wire_type, value) over one message."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            val, i = _read_varint(buf, i)
        elif wt == 1:  # 64-bit
            val, i = buf[i:i + 8], i + 8
        elif wt == 2:  # length-delimited
            ln, i = _read_varint(buf, i)
            val, i = buf[i:i + ln], i + ln
        elif wt == 5:  # 32-bit
            val, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fno, wt, val


@dataclass
class SpPiece:
    piece: str
    score: float
    type: int = _NORMAL


@dataclass
class SpModel:
    pieces: list[SpPiece] = field(default_factory=list)
    model_type: int = 1  # TrainerSpec.model_type: 1=unigram, 2=bpe
    normalizer_name: str = "nmt_nfkc"
    add_dummy_prefix: bool = True
    remove_extra_whitespaces: bool = True
    escape_whitespaces: bool = True
    unk_id: int = 0
    bos_id: int = 1
    eos_id: int = 2


def parse_model_proto(data: bytes) -> SpModel:
    """ModelProto wire layout (sentencepiece_model.proto): field 1 =
    repeated SentencePiece{piece:1, score:2, type:3}, field 2 =
    TrainerSpec{model_type:3, unk_id:40, bos_id:41, eos_id:42},
    field 4 = NormalizerSpec{add_dummy_prefix:3,
    remove_extra_whitespaces:4, escape_whitespaces:5}."""
    import struct

    m = SpModel()
    for fno, wt, val in _fields(data):
        if fno == 1 and wt == 2:  # SentencePiece
            piece, score, ptype = "", 0.0, _NORMAL
            for pf, pwt, pval in _fields(val):
                if pf == 1:
                    piece = pval.decode("utf-8", errors="replace")
                elif pf == 2 and pwt == 5:
                    score = struct.unpack("<f", pval)[0]
                elif pf == 3 and pwt == 0:
                    ptype = pval
            m.pieces.append(SpPiece(piece, score, ptype))
        elif fno == 2 and wt == 2:  # TrainerSpec
            for tf, twt, tval in _fields(val):
                if twt != 0:
                    continue
                # negative int32 ids (-1 = disabled, e.g. T5's bos_id) are
                # encoded as 64-bit two's-complement varints
                if tval >= 1 << 63:
                    tval -= 1 << 64
                if tf == 3:
                    m.model_type = tval
                elif tf == 40:
                    m.unk_id = tval
                elif tf == 41:
                    m.bos_id = tval
                elif tf == 42:
                    m.eos_id = tval
        elif fno == 4 and wt == 2:  # NormalizerSpec
            for nf, nwt, nval in _fields(val):
                if nf == 1 and nwt == 2:
                    m.normalizer_name = nval.decode(
                        "utf-8", errors="replace"
                    )
                if nwt != 0:
                    continue
                if nf == 3:
                    m.add_dummy_prefix = bool(nval)
                elif nf == 4:
                    m.remove_extra_whitespaces = bool(nval)
                elif nf == 5:
                    m.escape_whitespaces = bool(nval)
    return m


# ----------------------------------------------------------- tokenizer


@dataclass
class SpEncoding:
    ids: list[int]
    tokens: list[str]


class SentencePieceTokenizer:
    """Encoder/decoder over a parsed SpModel; HfTokenizer-duck-typed."""

    def __init__(self, model: SpModel) -> None:
        self.model = model
        self._piece_to_id: dict[str, int] = {}
        self._byte_ids: dict[int, int] = {}
        self._special: set[int] = set()
        self._max_piece_chars = 1
        for i, p in enumerate(model.pieces):
            self._piece_to_id.setdefault(p.piece, i)
            if p.type == _BYTE and len(p.piece) == 6:  # '<0xNN>'
                try:
                    self._byte_ids[int(p.piece[3:5], 16)] = i
                except ValueError:
                    pass
            if p.type in (_CONTROL, _UNKNOWN):
                self._special.add(i)
            if p.type in (_NORMAL, _USER_DEFINED):
                self._max_piece_chars = max(
                    self._max_piece_chars, len(p.piece)
                )
        # unk/byte fallback score: below any real piece (pure function of
        # the model — computed once, not per encode on the request path)
        self._fallback_score = min(
            (p.score for p in model.pieces if p.type == _NORMAL),
            default=0.0,
        ) - 10.0

    @classmethod
    def from_file(cls, path: str) -> "SentencePieceTokenizer":
        with open(path, "rb") as f:
            return cls(parse_model_proto(f.read()))

    # -------------------------------------------------------- normalize

    def _normalize(self, text: str) -> str:
        # honor NormalizerSpec.name: "identity" (llama-family) means no
        # unicode rewriting at all; nfkc-family normalizers apply NFKC,
        # and the nmt variants additionally fold control whitespace
        # (\t \n \r) to plain space before the escape step
        name = self.model.normalizer_name
        if name != "identity":
            if name.startswith("nmt"):
                text = text.translate(
                    {0x9: " ", 0xA: " ", 0xD: " "}
                )
            if "nfkc" in name or name == "":
                text = unicodedata.normalize("NFKC", text)
        if self.model.remove_extra_whitespaces:
            # collapse runs of spaces and trim ends, as SP's normalizer does
            text = " ".join(s for s in text.split(" ") if s)
        if self.model.add_dummy_prefix and text:
            text = " " + text
        if self.model.escape_whitespaces:
            text = text.replace(" ", SPACE_PIECE)
        return text

    # ----------------------------------------------------------- encode

    def encode(self, text: str, add_special_tokens: bool = True) -> SpEncoding:
        norm = self._normalize(text)
        if not norm:
            ids: list[int] = []
        elif self.model.model_type == 2:
            ids = self._encode_bpe(norm)
        else:
            ids = self._encode_unigram(norm)
        if add_special_tokens and self.model.bos_id >= 0:
            ids = [self.model.bos_id] + ids
        return SpEncoding(
            ids=ids,
            tokens=[self.model.pieces[i].piece for i in ids],
        )

    def _segment_fallback(self, ch: str) -> list[int]:
        """A character no piece covers: byte pieces, else unk."""
        out = []
        for b in ch.encode("utf-8"):
            bid = self._byte_ids.get(b)
            if bid is None:
                return [self.model.unk_id]
            out.append(bid)
        return out

    def _encode_unigram(self, s: str) -> list[int]:
        """Viterbi: best[i] = max-score segmentation of s[:i]."""
        n = len(s)
        NEG = float("-inf")
        best = [NEG] * (n + 1)
        back: list[Optional[tuple[int, list[int]]]] = [None] * (n + 1)
        best[0] = 0.0
        fallback_score = self._fallback_score
        for i in range(n):
            if best[i] == NEG:
                continue
            hi = min(n, i + self._max_piece_chars)
            for j in range(i + 1, hi + 1):
                pid = self._piece_to_id.get(s[i:j])
                if pid is None:
                    continue
                p = self.model.pieces[pid]
                if p.type in (_CONTROL, _UNKNOWN, _UNUSED, _BYTE):
                    continue
                sc = best[i] + p.score
                if sc > best[j]:
                    best[j] = sc
                    back[j] = (i, [pid])
            # single-char fallback edge
            j = i + 1
            sc = best[i] + fallback_score
            if sc > best[j]:
                best[j] = sc
                back[j] = (i, self._segment_fallback(s[i]))
        ids: list[int] = []
        j = n
        while j > 0:
            i, pids = back[j]  # type: ignore[misc]
            ids[:0] = pids
            j = i
        return ids

    def _encode_bpe(self, s: str) -> list[int]:
        """SentencePiece BPE: start from characters, repeatedly merge the
        adjacent pair whose concatenation is the best-scored piece.

        Heap-based merge queue (seed all pairs once, after a merge only
        its two new neighbor pairs are re-evaluated) — O(n log n), not the
        naive full rescan per merge, since this runs per request on the
        preprocessing hot path."""
        import heapq

        n = len(s)
        if n == 0:
            return []
        parts: list[Optional[str]] = list(s)
        prev = list(range(-1, n - 1))
        nxt = list(range(1, n + 1))

        def pair_entry(i: int):
            j = nxt[i]
            if j >= n or parts[i] is None or parts[j] is None:
                return None
            pid = self._piece_to_id.get(parts[i] + parts[j])
            if pid is None:
                return None
            # (neg score, position) — ties resolve leftmost like SP
            return (-self.model.pieces[pid].score, i, parts[i], parts[j])

        heap = [e for i in range(n) if (e := pair_entry(i)) is not None]
        heapq.heapify(heap)
        while heap:
            _, i, left, right = heapq.heappop(heap)
            j = nxt[i] if i < n else n
            # stale entry: one side already merged away
            if j >= n or parts[i] != left or parts[j] != right:
                continue
            parts[i] = left + right
            parts[j] = None
            nxt[i] = nxt[j]
            if nxt[j] < n:
                prev[nxt[j]] = i
            for k in (prev[i], i):
                if 0 <= k < n and (e := pair_entry(k)) is not None:
                    heapq.heappush(heap, e)
        ids: list[int] = []
        i = 0
        while 0 <= i < n:
            part = parts[i]
            if part is not None:
                pid = self._piece_to_id.get(part)
                if pid is not None and self.model.pieces[pid].type not in (
                    _CONTROL, _UNKNOWN, _UNUSED, _BYTE,
                ):
                    ids.append(pid)
                else:
                    for ch in part:
                        cid = self._piece_to_id.get(ch)
                        if cid is not None:
                            ids.append(cid)
                        else:
                            ids.extend(self._segment_fallback(ch))
            i = nxt[i]
        return ids

    # ----------------------------------------------------------- decode

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        out: list[str] = []
        byte_buf = bytearray()

        def flush_bytes():
            if byte_buf:
                out.append(byte_buf.decode("utf-8", errors="replace"))
                byte_buf.clear()

        for i in ids:
            if i < 0 or i >= len(self.model.pieces):
                continue
            p = self.model.pieces[i]
            if p.type == _BYTE:
                try:
                    byte_buf.append(int(p.piece[3:5], 16))
                    continue
                except ValueError:
                    pass
            flush_bytes()
            if skip_special_tokens and i in self._special:
                continue
            out.append(p.piece)
        flush_bytes()
        text = "".join(out).replace(SPACE_PIECE, " ")
        if self.model.add_dummy_prefix and text.startswith(" "):
            text = text[1:]
        return text

    # ---------------------------------------------- HfTokenizer surface

    def token_to_id(self, token: str) -> Optional[int]:
        return self._piece_to_id.get(token)

    def get_vocab_size(self) -> int:
        return len(self.model.pieces)

    def to_str(self) -> str:
        raise NotImplementedError(
            "SentencePiece models serialize as .model protobufs, not "
            "tokenizer.json — ship the original file"
        )


def serialize_model_proto(model: SpModel) -> bytes:
    """SpModel -> ModelProto wire bytes (inverse of parse_model_proto).

    Used when a tokenizer is constructed from somewhere other than a
    .model file (e.g. GGUF tokenizer.ggml metadata) but still needs the
    canonical byte form — model cards publish exactly these bytes."""
    import struct

    def varint(n: int) -> bytes:
        out = b""
        while True:
            b = n & 0x7F
            n >>= 7
            out += bytes([b | (0x80 if n else 0)])
            if not n:
                return out

    def ld(fno: int, payload: bytes) -> bytes:
        return varint((fno << 3) | 2) + varint(len(payload)) + payload

    def vi(fno: int, val: int) -> bytes:
        if val < 0:
            val += 1 << 64  # two's-complement (disabled ids are -1)
        return varint(fno << 3) + varint(val)

    def f32(fno: int, val: float) -> bytes:
        return varint((fno << 3) | 5) + struct.pack("<f", val)

    blob = b"".join(
        ld(1, ld(1, p.piece.encode()) + f32(2, p.score) + vi(3, p.type))
        for p in model.pieces
    )
    trainer = (
        vi(3, model.model_type) + vi(40, model.unk_id)
        + vi(41, model.bos_id) + vi(42, model.eos_id)
    )
    norm = ld(1, model.normalizer_name.encode()) + vi(
        3, int(model.add_dummy_prefix)
    ) + vi(4, int(model.remove_extra_whitespaces)) + vi(
        5, int(model.escape_whitespaces)
    )
    return blob + ld(2, trainer) + ld(4, norm)


def sp_model_path(model_dir: str) -> Optional[str]:
    for name in ("tokenizer.model", "spiece.model", "sentencepiece.model"):
        p = os.path.join(model_dir, name)
        if os.path.exists(p):
            return p
    return None
