"""GGUF model file reader (pure numpy + mmap).

Role-equivalent of lib/llm/src/gguf/ (the reference parses GGUF for
metadata/tokenizer/weights so `--model-path model.gguf` works end-to-end).
This reader covers the format surface the llama family needs:

  * full metadata KV section (all GGUF value types incl. nested arrays);
  * tensor directory (name, shape, dtype, offset) with lazy mmap views;
  * dtypes F32/F16/BF16 natively; Q4_0/Q4_1/Q5_0/Q5_1/Q8_0 and the
    K-quants Q4_K/Q5_K/Q6_K (what real published GGUFs like Q4_K_M
    actually contain) via vectorized dequantization;
  * `config_from_gguf` mapping llama.* metadata keys to LlamaConfig and
    `params_from_gguf` mapping ggml tensor names (token_embd, blk.N.*,
    output, ...) onto this repo's param tree, transposed to the [in, out]
    einsum orientation the model code uses.

Spec: https://github.com/ggml-org/ggml/blob/master/docs/gguf.md (public).
"""

from __future__ import annotations

import mmap
import struct
from dataclasses import dataclass
from typing import Any, BinaryIO, Optional

import numpy as np

GGUF_MAGIC = 0x46554747  # "GGUF" little-endian

# metadata value types
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32 = 0, 1, 2, 3, 4, 5
_T_F32, _T_BOOL, _T_STRING, _T_ARRAY, _T_U64, _T_I64, _T_F64 = (
    6, 7, 8, 9, 10, 11, 12,
)

_SCALAR_FMT = {
    _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
    _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_U64: "<Q",
    _T_I64: "<q", _T_F64: "<d",
}

# ggml tensor dtypes (subset)
GGML_F32, GGML_F16, GGML_BF16 = 0, 1, 30
GGML_Q4_0, GGML_Q4_1, GGML_Q5_0, GGML_Q5_1, GGML_Q8_0 = 2, 3, 6, 7, 8
GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 12, 13, 14
_GGML_NAMES = {GGML_F32: "F32", GGML_F16: "F16", GGML_BF16: "BF16",
               GGML_Q4_0: "Q4_0", GGML_Q4_1: "Q4_1", GGML_Q5_0: "Q5_0",
               GGML_Q5_1: "Q5_1", GGML_Q8_0: "Q8_0", GGML_Q4_K: "Q4_K",
               GGML_Q5_K: "Q5_K", GGML_Q6_K: "Q6_K"}

QK_K = 256  # K-quant super-block size

# bytes per block, elements per block — for tensor size validation
GGML_BLOCK = {
    GGML_Q4_0: (18, 32), GGML_Q4_1: (20, 32), GGML_Q5_0: (22, 32),
    GGML_Q5_1: (24, 32), GGML_Q8_0: (34, 32),
    GGML_Q4_K: (144, QK_K), GGML_Q5_K: (176, QK_K), GGML_Q6_K: (210, QK_K),
}


@dataclass
class GgufTensor:
    name: str
    shape: tuple[int, ...]  # logical (numpy, row-major) shape
    ggml_type: int
    offset: int  # relative to the data section

    @property
    def type_name(self) -> str:
        return _GGML_NAMES.get(self.ggml_type, f"unknown({self.ggml_type})")


def _read(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    return struct.unpack(fmt, f.read(size))[0]


def _read_string(f: BinaryIO) -> str:
    n = _read(f, "<Q")
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype == _T_STRING:
        return _read_string(f)
    if vtype == _T_BOOL:
        return bool(_read(f, "<B"))
    if vtype == _T_ARRAY:
        etype = _read(f, "<I")
        n = _read(f, "<Q")
        return [_read_value(f, etype) for _ in range(n)]
    fmt = _SCALAR_FMT.get(vtype)
    if fmt is None:
        raise ValueError(f"unknown gguf value type {vtype}")
    return _read(f, fmt)


class GgufFile:
    """Parsed GGUF container with lazy tensor access."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.metadata: dict[str, Any] = {}
        self.tensors: dict[str, GgufTensor] = {}
        with open(path, "rb") as f:
            magic = _read(f, "<I")
            if magic != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file (magic {magic:#x})")
            self.version = _read(f, "<I")
            if self.version < 2:
                raise ValueError(f"gguf v{self.version} unsupported (need >=2)")
            n_tensors = _read(f, "<Q")
            n_kv = _read(f, "<Q")
            for _ in range(n_kv):
                key = _read_string(f)
                vtype = _read(f, "<I")
                self.metadata[key] = _read_value(f, vtype)
            for _ in range(n_tensors):
                name = _read_string(f)
                ndim = _read(f, "<I")
                dims = [
                    _read(f, "<Q") for _ in range(ndim)
                ]  # ggml order: fastest-varying first
                ggml_type = _read(f, "<I")
                offset = _read(f, "<Q")
                self.tensors[name] = GgufTensor(
                    name=name,
                    shape=tuple(reversed(dims)),  # numpy row-major
                    ggml_type=ggml_type,
                    offset=offset,
                )
            align = int(self.metadata.get("general.alignment", 32))
            pos = f.tell()
            self.data_offset = (pos + align - 1) // align * align
        self._mm: Optional[mmap.mmap] = None
        self._file: Optional[BinaryIO] = None

    # ---------------------------------------------------------- tensors

    def _map(self) -> mmap.mmap:
        if self._mm is None:
            self._file = open(self.path, "rb")
            self._mm = mmap.mmap(
                self._file.fileno(), 0, access=mmap.ACCESS_READ
            )
        return self._mm

    def tensor(self, name: str) -> np.ndarray:
        """Materialize one tensor as numpy (dequantized if needed)."""
        import ml_dtypes

        t = self.tensors[name]
        mm = self._map()
        start = self.data_offset + t.offset
        numel = int(np.prod(t.shape))
        if t.ggml_type == GGML_F32:
            raw = np.frombuffer(mm, np.float32, numel, start)
            return raw.reshape(t.shape)
        if t.ggml_type == GGML_F16:
            raw = np.frombuffer(mm, np.float16, numel, start)
            return raw.reshape(t.shape)
        if t.ggml_type == GGML_BF16:
            raw = np.frombuffer(mm, np.uint16, numel, start)
            return raw.view(ml_dtypes.bfloat16).reshape(t.shape)
        deq = _DEQUANT.get(t.ggml_type)
        if deq is not None:
            _, elems = GGML_BLOCK[t.ggml_type]
            # ggml blocks never span rows: the fastest-varying dim must be
            # block-aligned, not just the total element count.
            if t.shape and t.shape[-1] % elems:
                raise ValueError(
                    f"tensor {name}: row length {t.shape[-1]} not divisible "
                    f"by {t.type_name} block size {elems}"
                )
            vals = deq(mm, numel // elems, start)
            return vals.reshape(t.shape).astype(np.float32, copy=False)
        raise NotImplementedError(
            f"tensor {name}: ggml type {t.type_name} not supported"
        )

    def close(self) -> None:
        if self._mm is not None:
            self._mm.close()
            self._mm = None
        if self._file is not None:
            self._file.close()
            self._file = None


# ---------------------------------------------------- block dequantization
#
# Vectorized numpy ports of the ggml block formats (spec: ggml quants.c,
# reference reader: lib/llm/src/gguf/).  Every function takes the mmap, a
# block count, and a byte offset and returns float32 [n_blocks, elems].

def _deq_q4_0(mm, n, start):
    rec = np.dtype([("d", "<f2"), ("qs", "u1", (16,))])
    raw = np.frombuffer(mm, rec, n, start)
    d = raw["d"].astype(np.float32)[:, None]
    lo = (raw["qs"] & 0x0F).astype(np.int8) - 8
    hi = (raw["qs"] >> 4).astype(np.int8) - 8
    return d * np.concatenate([lo, hi], axis=1).astype(np.float32)


def _deq_q4_1(mm, n, start):
    rec = np.dtype([("d", "<f2"), ("m", "<f2"), ("qs", "u1", (16,))])
    raw = np.frombuffer(mm, rec, n, start)
    d = raw["d"].astype(np.float32)[:, None]
    m = raw["m"].astype(np.float32)[:, None]
    q = np.concatenate([raw["qs"] & 0x0F, raw["qs"] >> 4], axis=1)
    return d * q.astype(np.float32) + m


def _deq_q5_0(mm, n, start):
    rec = np.dtype([("d", "<f2"), ("qh", "<u4"), ("qs", "u1", (16,))])
    raw = np.frombuffer(mm, rec, n, start)
    d = raw["d"].astype(np.float32)[:, None]
    j = np.arange(16)
    xh0 = ((raw["qh"][:, None] >> j) << 4) & 0x10
    xh1 = (raw["qh"][:, None] >> (j + 12)) & 0x10
    lo = ((raw["qs"] & 0x0F) | xh0).astype(np.int16) - 16
    hi = ((raw["qs"] >> 4) | xh1).astype(np.int16) - 16
    return d * np.concatenate([lo, hi], axis=1).astype(np.float32)


def _deq_q5_1(mm, n, start):
    rec = np.dtype(
        [("d", "<f2"), ("m", "<f2"), ("qh", "<u4"), ("qs", "u1", (16,))]
    )
    raw = np.frombuffer(mm, rec, n, start)
    d = raw["d"].astype(np.float32)[:, None]
    m = raw["m"].astype(np.float32)[:, None]
    j = np.arange(16)
    xh0 = ((raw["qh"][:, None] >> j) << 4) & 0x10
    xh1 = (raw["qh"][:, None] >> (j + 12)) & 0x10
    lo = (raw["qs"] & 0x0F) | xh0
    hi = (raw["qs"] >> 4) | xh1
    return d * np.concatenate([lo, hi], axis=1).astype(np.float32) + m


def _deq_q8_0(mm, n, start):
    rec = np.dtype([("d", "<f2"), ("q", "i1", (32,))])
    raw = np.frombuffer(mm, rec, n, start)
    return raw["q"].astype(np.float32) * raw["d"].astype(np.float32)[:, None]


def _unpack_scale_min_k4(s):
    """6-bit packed (scale, min) pairs for 8 sub-blocks; s is [n, 12] u8."""
    sc = np.empty(s.shape[:-1] + (8,), np.uint8)
    mn = np.empty_like(sc)
    sc[:, :4] = s[:, :4] & 63
    mn[:, :4] = s[:, 4:8] & 63
    sc[:, 4:] = (s[:, 8:12] & 0x0F) | ((s[:, 0:4] >> 6) << 4)
    mn[:, 4:] = (s[:, 8:12] >> 4) | ((s[:, 4:8] >> 6) << 4)
    return sc.astype(np.float32), mn.astype(np.float32)


def _deq_q4_k(mm, n, start):
    rec = np.dtype([("d", "<f2"), ("dmin", "<f2"),
                    ("scales", "u1", (12,)), ("qs", "u1", (128,))])
    raw = np.frombuffer(mm, rec, n, start)
    d = raw["d"].astype(np.float32)
    dmin = raw["dmin"].astype(np.float32)
    sc, mn = _unpack_scale_min_k4(raw["scales"])
    qs = raw["qs"].reshape(n, 4, 32)
    # chunk j yields sub-blocks 2j (low nibbles) then 2j+1 (high nibbles)
    q = np.stack([qs & 0x0F, qs >> 4], axis=2).reshape(n, 8, 32)
    vals = (d[:, None, None] * sc[:, :, None] * q.astype(np.float32)
            - dmin[:, None, None] * mn[:, :, None])
    return vals.reshape(n, QK_K)


def _deq_q5_k(mm, n, start):
    rec = np.dtype([("d", "<f2"), ("dmin", "<f2"), ("scales", "u1", (12,)),
                    ("qh", "u1", (32,)), ("qs", "u1", (128,))])
    raw = np.frombuffer(mm, rec, n, start)
    d = raw["d"].astype(np.float32)
    dmin = raw["dmin"].astype(np.float32)
    sc, mn = _unpack_scale_min_k4(raw["scales"])
    qs = raw["qs"].reshape(n, 4, 32)
    qh = raw["qh"][:, None, :]
    jj = np.arange(4)[None, :, None]
    # 5th bit of sub-block 2j lives at qh bit 2j, of 2j+1 at bit 2j+1
    lo = (qs & 0x0F) + (((qh >> (2 * jj)) & 1) << 4)
    hi = (qs >> 4) + (((qh >> (2 * jj + 1)) & 1) << 4)
    q = np.stack([lo, hi], axis=2).reshape(n, 8, 32)
    vals = (d[:, None, None] * sc[:, :, None] * q.astype(np.float32)
            - dmin[:, None, None] * mn[:, :, None])
    return vals.reshape(n, QK_K)


def _deq_q6_k(mm, n, start):
    rec = np.dtype([("ql", "u1", (128,)), ("qh", "u1", (64,)),
                    ("scales", "i1", (16,)), ("d", "<f2")])
    raw = np.frombuffer(mm, rec, n, start)
    d = raw["d"].astype(np.float32)
    ql = raw["ql"].reshape(n, 2, 2, 32)   # [n, half, lo32/hi32-bytes, 32]
    qh = raw["qh"].reshape(n, 2, 32)
    sc = raw["scales"].reshape(n, 2, 8).astype(np.float32)
    ql_a, ql_b = ql[:, :, 0], ql[:, :, 1]
    q = np.stack([
        (ql_a & 0x0F) | (((qh >> 0) & 3) << 4),
        (ql_b & 0x0F) | (((qh >> 2) & 3) << 4),
        (ql_a >> 4) | (((qh >> 4) & 3) << 4),
        (ql_b >> 4) | (((qh >> 6) & 3) << 4),
    ], axis=2).astype(np.int16) - 32        # [n, 2, 4, 32]
    # output y[l + 32k] scales with scales[l//16 + 2k] within each half
    sidx = (np.arange(32) // 16)[None, :] + 2 * np.arange(4)[:, None]
    vals = d[:, None, None, None] * sc[:, :, sidx] * q.astype(np.float32)
    return vals.reshape(n, QK_K)


_DEQUANT = {
    GGML_Q4_0: _deq_q4_0, GGML_Q4_1: _deq_q4_1, GGML_Q5_0: _deq_q5_0,
    GGML_Q5_1: _deq_q5_1, GGML_Q8_0: _deq_q8_0, GGML_Q4_K: _deq_q4_k,
    GGML_Q5_K: _deq_q5_k, GGML_Q6_K: _deq_q6_k,
}


# ------------------------------------------------------- embedded tokenizer


def tokenizer_from_gguf(g: GgufFile):
    """TokenizerWrapper from the file's own tokenizer.ggml.* metadata.

    Real published GGUFs embed their tokenizer (reference:
    lib/llm/src/gguf/gguf_tokenizer.rs convert_gguf_to_hf_tokenizer); the
    llama-family model ("llama"/"replit": SentencePiece pieces + scores)
    maps 1:1 onto our native SP engine — GGUF token_type uses the same
    enum as SentencePiece piece types (1 normal, 2 unknown, 3 control,
    6 byte). Returns None when the metadata carries no tokenizer; raises
    for tokenizer models we don't support (gpt2 byte-BPE needs merges —
    ship a tokenizer.json next to the file for those)."""
    md = g.metadata
    tokens = md.get("tokenizer.ggml.tokens")
    if not tokens:
        return None
    model_name = md.get("tokenizer.ggml.model", "llama")
    if model_name not in ("llama", "replit"):
        raise NotImplementedError(
            f"GGUF tokenizer model {model_name!r} unsupported — place a "
            "tokenizer.json next to the .gguf file"
        )
    from dynamo_tpu.sp_tokenizer import (
        SpModel,
        SpPiece,
        serialize_model_proto,
    )
    from dynamo_tpu.tokenizer import TokenizerWrapper

    scores = md.get("tokenizer.ggml.scores") or [0.0] * len(tokens)
    types = md.get("tokenizer.ggml.token_type") or [1] * len(tokens)
    if len(scores) != len(tokens) or len(types) != len(tokens):
        # zip() would silently truncate the vocab; corrupt files must fail
        raise ValueError(
            f"corrupt GGUF tokenizer metadata: {len(tokens)} tokens vs "
            f"{len(scores)} scores / {len(types)} token types"
        )
    model = SpModel(
        pieces=[
            SpPiece(t, float(s), int(ty))
            for t, s, ty in zip(tokens, scores, types)
        ],
        model_type=1,  # SP scores -> unigram Viterbi (llama.cpp SPM)
        # llama-family SPM semantics: identity normalizer, whitespace kept
        # verbatim (newlines ride byte-fallback pieces — folding them to
        # spaces would tokenize differently than llama.cpp does)
        normalizer_name="identity",
        remove_extra_whitespaces=False,
        unk_id=int(md.get("tokenizer.ggml.unknown_token_id", 0)),
        bos_id=int(md.get("tokenizer.ggml.bos_token_id", 1)),
        eos_id=int(md.get("tokenizer.ggml.eos_token_id", 2)),
        add_dummy_prefix=bool(md.get("tokenizer.ggml.add_space_prefix", True)),
    )
    # round-trip through the canonical byte form so the tokenizer a worker
    # serves is BY CONSTRUCTION the one the model card publishes
    return TokenizerWrapper.from_sp_bytes(serialize_model_proto(model))


# --------------------------------------------------------------- mapping


def config_from_gguf(g: GgufFile):
    """llama.* metadata -> LlamaConfig."""
    from dynamo_tpu.models.llama import LlamaConfig

    md = g.metadata
    arch = md.get("general.architecture", "llama")

    def key(suffix, default=None):
        return md.get(f"{arch}.{suffix}", default)

    n_heads = int(key("attention.head_count", 32))
    hidden = int(key("embedding_length", 4096))
    n_vocab = md.get(f"{arch}.vocab_size") or (
        len(md.get("tokenizer.ggml.tokens", [])) or 32000
    )
    if arch.startswith("gemma") and arch not in ("gemma", "gemma2", "gemma3"):
        # gemma3n etc.: architectures we haven't mapped — refuse rather
        # than load as a silently-wrong plain llama
        raise NotImplementedError(
            f"GGUF architecture {arch!r} not supported"
        )
    gemma_like = arch.startswith("gemma")
    num_layers = int(key("block_count", 32))
    sliding = key("attention.sliding_window")
    layer_pattern = None
    if arch == "gemma2" and sliding:
        layer_pattern = tuple(i % 2 == 0 for i in range(num_layers))
    elif arch == "gemma3" and sliding:
        layer_pattern = tuple((i + 1) % 6 != 0 for i in range(num_layers))
    return LlamaConfig(
        attn_bias=arch.startswith("qwen2"),
        mlp_act="gelu_tanh" if gemma_like else "silu",
        embed_scale=gemma_like,
        norm_plus_one=gemma_like,
        tie_word_embeddings=gemma_like,
        vocab_size=int(n_vocab),
        hidden_size=hidden,
        intermediate_size=int(key("feed_forward_length", 4 * hidden)),
        num_layers=num_layers,
        num_heads=n_heads,
        num_kv_heads=int(key("attention.head_count_kv", n_heads)),
        head_dim=int(key("attention.key_length", hidden // n_heads)),
        rope_theta=float(key("rope.freq_base", 10000.0)),
        rms_eps=float(key("attention.layer_norm_rms_epsilon", 1e-5)),
        max_position_embeddings=int(key("context_length", 8192)),
        sliding_window=int(sliding) if sliding else None,
        layer_pattern=layer_pattern,
        attn_logit_softcap=(
            float(key("attn_logit_softcapping", 50.0))
            if arch == "gemma2" else None
        ),
        final_logit_softcap=(
            float(key("final_logit_softcapping", 30.0))
            if arch == "gemma2" else None
        ),
        query_pre_attn_scalar=(
            float(key("attention.query_pre_attn_scalar"))
            if key("attention.query_pre_attn_scalar") else None
        ),
        sandwich_norms=arch in ("gemma2", "gemma3"),
        qk_norm=arch == "gemma3",
        rope_local_theta=(
            float(key("rope.local_freq_base", 10000.0))
            if arch == "gemma3" else None
        ),
    )


# ggml name -> (our key, needs_transpose). Projection matrices are stored
# [out, in] in ggml; our einsums are x @ W with W [in, out].
_LAYER_MAP = {
    "attn_norm.weight": ("attn_norm", False),
    "attn_q.weight": ("wq", True),
    "attn_k.weight": ("wk", True),
    "attn_v.weight": ("wv", True),
    "attn_output.weight": ("wo", True),
    "ffn_norm.weight": ("mlp_norm", False),
    "ffn_gate.weight": ("wg", True),
    "ffn_up.weight": ("wu", True),
    "ffn_down.weight": ("wd", True),
}

# gemma2/3 extras (absent in llama-family files; loaded when present)
_OPTIONAL_LAYER_MAP = {
    "post_attention_norm.weight": ("post_attn_norm", False),
    "post_ffw_norm.weight": ("post_mlp_norm", False),
    "attn_q_norm.weight": ("q_norm", False),
    "attn_k_norm.weight": ("k_norm", False),
}


def params_from_gguf(g: GgufFile, cfg=None, dtype=None):
    """Materialize this repo's llama param tree from a GGUF file."""
    import jax.numpy as jnp
    import ml_dtypes

    cfg = cfg or config_from_gguf(g)
    dtype = dtype or ml_dtypes.bfloat16

    def get(name, transpose=False, plus_one=False):
        a = g.tensor(name)
        if transpose:
            a = a.T
        if plus_one and cfg.norm_plus_one:  # gemma (1+w) RMSNorm weights
            a = a + 1
        return jnp.asarray(np.ascontiguousarray(a).astype(dtype))

    params: dict[str, Any] = {
        "embed": get("token_embd.weight"),
        "final_norm": get("output_norm.weight", plus_one=True),
        "layers": [],
    }
    if "output.weight" in g.tensors:
        params["lm_head"] = get("output.weight", transpose=True)
    for i in range(cfg.num_layers):
        layer = {}
        for suffix, (ours, tr) in _LAYER_MAP.items():
            layer[ours] = get(
                f"blk.{i}.{suffix}", transpose=tr,
                plus_one=ours in ("attn_norm", "mlp_norm"),
            )
        for suffix, (ours, tr) in _OPTIONAL_LAYER_MAP.items():
            if f"blk.{i}.{suffix}" in g.tensors:
                layer[ours] = get(
                    f"blk.{i}.{suffix}", transpose=tr, plus_one=True
                )
        # qwen2-family q/k/v biases, when the file ships them
        for suffix, ours in (
            ("attn_q.bias", "bq"), ("attn_k.bias", "bk"),
            ("attn_v.bias", "bv"),
        ):
            if f"blk.{i}.{suffix}" in g.tensors:
                layer[ours] = get(f"blk.{i}.{suffix}")
        params["layers"].append(layer)
    return cfg, params
