"""Token sequences and the KV-block hash chain.

Role-equivalent of the reference's lib/tokens crate + lib/llm/src/tokens.rs:
a token sequence is chunked into fixed-size blocks; each complete block gets a
chained hash `h_i = H(h_{i-1}, tokens_i, salt)` (lib/tokens/src/lib.rs:221).
These block hashes are THE shared currency between the KV-aware router, the
engine's paged cache, and the multi-tier block manager: equal hash chain
prefix <=> reusable KV prefix.

Hash: 64-bit from blake2b (stdlib, stable across processes/languages).
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field
from typing import Iterable, Optional

DEFAULT_BLOCK_SIZE = 16


def compute_block_hash(
    parent_hash: int, tokens: list[int], salt: int = 0
) -> int:
    from dynamo_tpu import native

    got = native.block_hash(parent_hash, tokens, salt)
    if got is not None:
        return got
    return _py_block_hash(parent_hash, tokens, salt)


def _py_block_hash(parent_hash: int, tokens: list[int], salt: int = 0) -> int:
    h = hashlib.blake2b(digest_size=8)
    h.update(struct.pack("<QQ", parent_hash & 0xFFFFFFFFFFFFFFFF, salt))
    h.update(struct.pack(f"<{len(tokens)}I", *tokens))
    return struct.unpack("<Q", h.digest())[0]


def compute_seq_hash_chain(
    tokens: list[int], block_size: int = DEFAULT_BLOCK_SIZE, salt: int = 0
) -> list[int]:
    """Hashes of all COMPLETE blocks of the sequence.

    Dispatches to the native C implementation (dynamo_tpu/native —
    bit-identical digests) when available; router/indexer call this for
    every scheduled prompt."""
    from dynamo_tpu import native

    got = native.hash_chain(tokens, block_size, salt)
    if got is not None:
        return got
    return _py_seq_hash_chain(tokens, block_size, salt)


def _py_seq_hash_chain(
    tokens: list[int], block_size: int = DEFAULT_BLOCK_SIZE, salt: int = 0
) -> list[int]:
    hashes: list[int] = []
    parent = 0
    for start in range(0, len(tokens) - len(tokens) % block_size, block_size):
        parent = _py_block_hash(
            parent, tokens[start : start + block_size], salt
        )
        hashes.append(parent)
    return hashes


@dataclass
class TokenBlock:
    """A complete, hashed block of tokens."""

    tokens: list[int]
    block_hash: int
    parent_hash: int
    position: int  # block index within the sequence


@dataclass
class PartialTokenBlock:
    tokens: list[int] = field(default_factory=list)

    def remaining(self, block_size: int) -> int:
        return block_size - len(self.tokens)


class TokenBlockSequence:
    """Incremental block/hash bookkeeping for a growing token sequence.

    (reference lib/tokens/src/lib.rs:277 TokenBlockSequence)"""

    def __init__(
        self,
        tokens: Optional[Iterable[int]] = None,
        block_size: int = DEFAULT_BLOCK_SIZE,
        salt: int = 0,
    ) -> None:
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = block_size
        self.salt = salt
        self.blocks: list[TokenBlock] = []
        self.partial = PartialTokenBlock()
        if tokens:
            self.extend(list(tokens))

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self.partial.tokens)

    @property
    def tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.partial.tokens)
        return out

    def block_hashes(self) -> list[int]:
        return [b.block_hash for b in self.blocks]

    def last_hash(self) -> int:
        return self.blocks[-1].block_hash if self.blocks else 0

    def append(self, token: int) -> Optional[TokenBlock]:
        """Append one token; returns the newly completed block, if any."""
        self.partial.tokens.append(token)
        if len(self.partial.tokens) == self.block_size:
            parent = self.last_hash()
            blk = TokenBlock(
                tokens=self.partial.tokens,
                block_hash=compute_block_hash(parent, self.partial.tokens, self.salt),
                parent_hash=parent,
                position=len(self.blocks),
            )
            self.blocks.append(blk)
            self.partial = PartialTokenBlock()
            return blk
        return None

    def extend(self, tokens: list[int]) -> list[TokenBlock]:
        """Append many tokens; returns all newly completed blocks."""
        new_blocks: list[TokenBlock] = []
        for t in tokens:
            blk = self.append(t)
            if blk is not None:
                new_blocks.append(blk)
        return new_blocks

    def truncate(self, num_tokens: int) -> None:
        if num_tokens >= len(self):
            return
        toks = self.tokens[:num_tokens]
        self.blocks = []
        self.partial = PartialTokenBlock()
        self.extend(toks)
