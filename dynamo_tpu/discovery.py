"""Model discovery: workers register models; frontends watch and wire chains.

Role-equivalent of lib/llm/src/discovery/{watcher,model_manager,model_entry}.rs
and the bindings' `register_llm`: a worker publishes its ModelDeploymentCard
to the fabric object store and writes a lease-bound kv entry under `models/`;
every frontend's ModelWatcher sees the entry, downloads the card, builds the
preprocessor -> router -> backend chain, and registers it with its
ModelManager. Lease death removes the entry and (on last ref) the model.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import os
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Optional

from dynamo_tpu import qos
from dynamo_tpu.http.service import ModelExecution, ModelManager
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.pipeline.annotated import Annotated
from dynamo_tpu.pipeline.context import Context
from dynamo_tpu.pipeline.router import PushRouter, RouterMode
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.runtime.backoff import full_jitter_delay
from dynamo_tpu.runtime.component import Endpoint, NoInstancesError
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime import clock as dclock
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.protocols import MODEL_ROOT, EndpointId
from dynamo_tpu.telemetry import health as dhealth
from dynamo_tpu.telemetry import provenance as dprov
from dynamo_tpu.telemetry import trace as dtrace

logger = get_logger("dynamo_tpu.discovery")


@dataclass
class ModelEntry:
    """The kv record under models/ (reference discovery/model_entry.rs)."""

    name: str
    slug: str
    endpoint: str  # dyn://ns.comp.ep
    model_type: str = "both"

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "ModelEntry":
        d = json.loads(raw)
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


async def register_llm(
    drt: DistributedRuntime,
    endpoint: Endpoint,
    mdc: ModelDeploymentCard,
    lease_id: Optional[int] = None,
) -> str:
    """Publish the model card + discovery entry for a serving worker.

    Returns the kv key (which dies with the lease)."""
    await mdc.publish(drt.fabric)
    lid = lease_id if lease_id is not None else drt.primary_lease
    entry = ModelEntry(
        name=mdc.name,
        slug=mdc.slug,
        endpoint=str(endpoint.id),
        model_type=mdc.model_type,
    )
    key = f"{MODEL_ROOT}{mdc.slug}:{lid:x}"
    await drt.fabric.kv_put(key, entry.to_bytes(), lease_id=lid)
    logger.info("registered model %s -> %s", mdc.name, entry.endpoint)
    return key


class _ResumedStream:
    """ResponseStream facade that resumes iteration after the hedging
    logic pulled (or started pulling) the first frame: yields the pending
    first item, then delegates to the underlying iterator. close()
    cancels the pending pull and closes the inner stream (killing its
    per-attempt context — the CancellationToken cascade the engines
    already honor for consumer disconnects)."""

    def __init__(self, inner: Any, it: Any, pending: Optional[asyncio.Task]):
        self._inner = inner
        self._it = it
        self._pending = pending
        self.context = inner.context

    def __aiter__(self):
        async def gen():
            try:
                if self._pending is not None:
                    item = await self._pending
                    self._pending = None
                    yield item
                while True:
                    yield await self._it.__anext__()
            except StopAsyncIteration:
                return

        return gen()

    async def close(self) -> None:
        if self._pending is not None:
            self._pending.cancel()
            self._pending = None
        await self._inner.close()


def _first_frame_tokens(task: asyncio.Task) -> int:
    """Tokens carried by a completed first-frame pull (0 for errors)."""
    if not task.done() or task.cancelled() or task.exception() is not None:
        return 0
    item = task.result()
    data = getattr(item, "data", None)
    if isinstance(data, dict):
        return len(data.get("token_ids") or ())
    return 0


def _is_good_first_frame(task: asyncio.Task) -> bool:
    """A completed pull that yielded a non-error data frame."""
    if not task.done() or task.cancelled() or task.exception() is not None:
        return False
    item = task.result()
    return not item.is_error()


class RemoteEngine:
    """EngineFn adapter with in-flight migration: forwards
    PreprocessedRequests over a PushRouter; when the serving worker dies
    mid-stream (transport error frame, handshake timeout, or the response
    stream breaking without a finish_reason — the signatures of a killed
    decode worker or a lost discovery lease), the request is REPLAYED —
    prompt plus already-emitted tokens — onto another healthy worker via
    the engines' `resume_prompt_len` replay contract, under bounded retries
    with exponential backoff + jitter. The resumed stream carries no
    duplicated and no dropped tokens: every engine counts the replayed tail
    as generated output, so budgets and per-token RNG counters continue
    exactly where the dead worker stopped.

    Tail tolerance (ISSUE 12): with a `health` scorer wired, every
    dispatch / first-frame / inter-frame latency is recorded against the
    serving worker (the consumer-observed half of gray-failure
    detection), and ejected stragglers are excluded from replays. With
    `DYN_HEDGE=1` and a `hedger`, an interactive request whose first
    token hasn't arrived within the dynamic hedge delay launches ONE
    hedge dispatch on a different worker; the first stream to produce a
    token wins and the loser is cancelled through the normal
    CancellationToken cascade (freeing its lane + KV). A hedge is a
    FRESH dispatch of the same request — not a replay — so per-token
    threefry counters line up and hedged streams are token-identical
    under greedy and seeded sampling."""

    def __init__(
        self,
        router: PushRouter,
        on_migration: Optional[Callable[[], None]] = None,
        cancel_token: Optional[Any] = None,
        fences: Optional[Any] = None,  # runtime.fencing.FenceRegistry
        on_fenced_reject: Optional[Callable[[], None]] = None,
        health: Optional[Any] = None,  # telemetry.health.HealthScorer
        hedger: Optional[Any] = None,  # telemetry.health.HedgeController
    ) -> None:
        self.router = router
        self.on_migration = on_migration
        self.health = health
        self.hedger = hedger
        # DYN_HEDGE resolved once: the disabled fast path is this single
        # attribute check per request (PR 5/6 overhead discipline)
        self._hedge = hedger is not None and dhealth.hedge_enabled()
        # the hosting runtime's CancellationToken: when the frontend itself
        # is dying (fabric/lease loss), replays must abort IMMEDIATELY so
        # the structured error still reaches the client before teardown
        self.cancel_token = cancel_token
        # epoch fencing: reply frames stamped with a fenced epoch (a
        # partitioned zombie still streaming after the cluster declared it
        # dead) are rejected and the request replays onto a live worker
        self.fences = fences
        self.on_fenced_reject = on_fenced_reject
        self.max_retries = int(os.environ.get("DYN_MIGRATION_MAX_RETRIES", "4"))
        self.backoff_base_s = float(
            os.environ.get("DYN_MIGRATION_BACKOFF_S", "0.05")
        )
        self.dispatch_timeout_s = float(
            os.environ.get("DYN_MIGRATION_DISPATCH_TIMEOUT_S", "5")
        )

    def _runtime_dying(self) -> bool:
        return self.cancel_token is not None and self.cancel_token.is_cancelled()

    async def _hedged_first(
        self,
        stream: Any,
        ctx: Context,
        attempt_ctx: Context,
        req_dict: dict,
        exclude: set[int],
        dsp: Any,
    ) -> Any:
        """Hedged first token ("The Tail at Scale"): wait the dynamic
        hedge delay for the primary's first frame; past it, launch ONE
        hedge dispatch on a different eligible worker (budget
        permitting), race the two first frames, keep the winner, and
        cancel the loser. Always returns a stream-like to iterate — on
        any internal failure the primary passes through untouched."""
        hedger = self.hedger
        it = stream.__aiter__()
        first_task = asyncio.ensure_future(it.__anext__())
        done, _ = await asyncio.wait(
            {first_task}, timeout=hedger.delay_ms() / 1e3
        )
        if done:
            # primary answered inside the delay: the common case — no
            # hedge, no extra dispatch
            return _ResumedStream(stream, it, first_task)
        if not hedger.try_acquire():  # counts outcome=budget_denied
            dsp.set(hedge="budget_denied")
            if dprov.enabled():
                dprov.record(
                    "remote", "hedge", None,
                    reason="budget_denied", ctx=ctx,
                )
            return _ResumedStream(stream, it, first_task)
        primary_wid = attempt_ctx.metadata.get("worker_instance_id")
        hx = set(exclude)
        if primary_wid is not None:
            hx.add(primary_wid)
        # the hedge context is a SIBLING of the primary's attempt context
        # (both children of the request ctx): cancelling the loser must
        # not cascade into the winner
        hedge_ctx = ctx.child()
        hstream = None
        try:
            hstream = await asyncio.wait_for(
                self.router.generate(req_dict, hedge_ctx, exclude=hx),
                self.dispatch_timeout_s,
            )
        except Exception as e:  # noqa: BLE001 — a failed hedge is a no-op
            dtrace.event("hedge_dispatch_failed", cause=str(e))
        if hstream is None:
            hedger.note_outcome("lost")
            return _ResumedStream(stream, it, first_task)
        hedge_wid = hedge_ctx.metadata.get("worker_instance_id")
        dsp.set(
            hedged=True,
            hedge_worker=f"{hedge_wid:x}" if hedge_wid is not None else None,
        )
        hit = hstream.__aiter__()
        hedge_task = asyncio.ensure_future(hit.__anext__())
        await asyncio.wait(
            {first_task, hedge_task}, return_when=asyncio.FIRST_COMPLETED
        )
        # pick the winner: the first GOOD frame; prefer the primary on a
        # tie (no switch); a side whose pull errored loses even if first
        primary_good = _is_good_first_frame(first_task)
        hedge_good = _is_good_first_frame(hedge_task)
        if primary_good:
            hedge_wins = False
        elif hedge_good:
            hedge_wins = True
        elif first_task.done() and not hedge_task.done():
            # primary's first pull failed while the hedge is still in
            # flight: ride the hedge rather than burning a migration
            hedge_wins = True
        else:
            # hedge failed first (or both failed): stay on the primary —
            # the outer failure/migration logic owns what happens next
            hedge_wins = False
        if hedge_wins:
            wasted = _first_frame_tokens(first_task)
            first_task.cancel()
            with contextlib.suppress(Exception):
                await stream.close()
            hedger.note_outcome("won", wasted_tokens=wasted)
            dsp.set(hedge="won")
            dtrace.event(
                "hedge_won",
                loser=f"{primary_wid:x}" if primary_wid is not None else None,
            )
            if dprov.enabled():
                dprov.record(
                    "remote", "hedge",
                    f"{hedge_wid:x}" if hedge_wid is not None else None,
                    reason="won", ctx=ctx,
                    alternatives=[
                        {
                            "worker": (
                                f"{primary_wid:x}"
                                if primary_wid is not None else None
                            ),
                            "outcome": "lost",
                        }
                    ],
                    wasted_tokens=wasted,
                )
            # downstream bookkeeping (failure exclusion, health
            # attribution) follows the worker actually serving the stream
            if hedge_wid is not None:
                attempt_ctx.metadata["worker_instance_id"] = hedge_wid
            return _ResumedStream(hstream, hit, hedge_task)
        wasted = _first_frame_tokens(hedge_task)
        hedge_task.cancel()
        with contextlib.suppress(Exception):
            await hstream.close()
        hedger.note_outcome("lost", wasted_tokens=wasted)
        dsp.set(hedge="lost")
        if dprov.enabled():
            dprov.record(
                "remote", "hedge",
                f"{primary_wid:x}" if primary_wid is not None else None,
                reason="lost", ctx=ctx,
                alternatives=[
                    {
                        "worker": (
                            f"{hedge_wid:x}"
                            if hedge_wid is not None else None
                        ),
                        "outcome": "cancelled",
                    }
                ],
                wasted_tokens=wasted,
            )
        return _ResumedStream(stream, it, first_task)

    async def __call__(
        self, request: PreprocessedRequest, ctx: Context
    ) -> AsyncIterator[LLMEngineOutput]:
        prompt_len = len(request.token_ids)
        emitted: list[int] = []
        failures = 0  # consecutive failed attempts (reset on progress)
        exclude: set[int] = set()
        req_dict = request.to_dict()
        # vision requests carry side-channel embeddings keyed off the live
        # worker; a mid-stream replay cannot reproduce them faithfully
        can_replay = not any(
            k in request.extra for k in ("mm", "mm_images", "mm_videos")
        )
        # hedging applies to interactive-class first attempts only (tail
        # latency is an interactive problem; bulk work can wait out a
        # straggler) and requires replayability for the same reason
        # migration does: the hedge must reproduce the stream exactly
        hedge_this = (
            self._hedge
            and can_replay
            and qos.priority_of(ctx, request) == "interactive"
        )
        attempt = 0
        while True:
            # per-attempt child context: closing a dead attempt's stream
            # kills only the child, not the request
            attempt_ctx = ctx.child()
            attempt += 1
            failure: Optional[str] = None
            progressed = False
            no_instances = False
            stream = None
            # per-attempt dispatch span: replays share the request's trace
            # id (ctx carries it), so a migrated stream is ONE trace with
            # one dispatch span per attempt, all parented to the root
            t_attempt = dclock.now()
            t_first: Optional[float] = None
            t_last_frame: Optional[float] = None
            with dtrace.span(
                "dispatch", ctx=attempt_ctx, attach=True, attempt=attempt,
                replayed_tokens=len(emitted),
            ) as dsp:
                try:
                    # bounded dispatch, raced against runtime shutdown: a
                    # dead fabric's failover hunt must not hang the replay
                    # past the frontend's own teardown
                    dispatch = self.router.generate(
                        req_dict, attempt_ctx, exclude=exclude or None
                    )
                    if self.cancel_token is not None:
                        stream = await asyncio.wait_for(
                            self.cancel_token.run_until_cancelled(dispatch),
                            self.dispatch_timeout_s,
                        )
                        if stream is None:
                            failure = "frontend runtime shutting down"
                    else:
                        stream = await asyncio.wait_for(
                            dispatch, self.dispatch_timeout_s
                        )
                except asyncio.TimeoutError:
                    failure = (
                        f"dispatch timed out after "
                        f"{self.dispatch_timeout_s:.1f}s"
                    )
                except Exception as e:  # noqa: BLE001 — dispatch failure
                    failure = f"dispatch failed: {type(e).__name__}: {e}"
                    no_instances = isinstance(e, NoInstancesError)
                if stream is not None:
                    wid = attempt_ctx.metadata.get("worker_instance_id")
                    if self.health is not None and wid is not None:
                        self.health.record(
                            wid, "dispatch",
                            (dclock.now() - t_attempt) * 1e3,
                        )
                    if self.hedger is not None:
                        self.hedger.note_dispatch()
                    if hedge_this and attempt == 1 and not emitted:
                        stream = await self._hedged_first(
                            stream, ctx, attempt_ctx, req_dict, exclude, dsp
                        )
                        # the hedge may have won: exclusion bookkeeping
                        # and health attribution follow the live worker
                        wid = attempt_ctx.metadata.get("worker_instance_id")
                    if wid is not None:
                        dsp.set(worker=f"{wid:x}")
                    finished = False
                    try:
                        async for item in stream:
                            if item.is_error():
                                failure = (
                                    item.error_message()
                                    or "worker stream error"
                                )
                                break
                            if item.data is not None:
                                stamp = (
                                    item.data.get("stamp")
                                    if isinstance(item.data, dict)
                                    else None
                                )
                                if (
                                    self.fences is not None
                                    and self.fences.check_stamp(
                                        stamp, "dispatch"
                                    )
                                ):
                                    # zombie worker: the cluster fenced its
                                    # epoch — refuse the frame and migrate
                                    failure = (
                                        "worker epoch "
                                        f"{stamp.get('ep', 0):x} is fenced"
                                    )
                                    if self.on_fenced_reject is not None:
                                        with contextlib.suppress(Exception):
                                            self.on_fenced_reject()
                                    break
                                out = LLMEngineOutput.from_dict(item.data)
                                if out.trace:
                                    # worker shipped its completed spans on
                                    # the final frame: fold them into this
                                    # process's ring for trace assembly
                                    dtrace.ingest(out.trace)
                                    out.trace = None
                                if out.decisions:
                                    # same contract for decision records:
                                    # the worker's why-ledger entries merge
                                    # into the frontend's ledger
                                    dprov.ingest(out.decisions)
                                    out.decisions = None
                                if out.token_ids:
                                    emitted.extend(out.token_ids)
                                    progressed = True
                                    if self.health is not None:
                                        now = dclock.now()
                                        if t_first is None:
                                            t_first = now
                                            ms = (now - t_attempt) * 1e3
                                            if wid is not None:
                                                self.health.record(
                                                    wid, "first_frame", ms
                                                )
                                            if self.hedger is not None:
                                                self.hedger.note_first_frame(
                                                    ms
                                                )
                                        elif (
                                            wid is not None
                                            and t_last_frame is not None
                                        ):
                                            self.health.record(
                                                wid, "inter_frame",
                                                (now - t_last_frame) * 1e3,
                                            )
                                        t_last_frame = now
                                yield out
                                if out.finish_reason is not None:
                                    finished = True
                                    return
                    except (ConnectionError, OSError) as e:
                        failure = f"stream broke: {e}"
                    finally:
                        with contextlib.suppress(Exception):
                            await stream.close()
                    if failure is None and not finished:
                        # EOF with no final: the response plane died
                        failure = "stream ended without a finish reason"
                if failure is not None:
                    dsp.set(failure=failure)
            # ---- the attempt failed; decide whether to migrate ----
            if ctx.is_killed() or ctx.is_stopped():
                yield LLMEngineOutput.final(FinishReason.CANCELLED)
                return
            if self._runtime_dying():
                # frontend is being torn down (fabric/lease loss): emit the
                # structured final NOW, while the response can still flush
                yield LLMEngineOutput.final_error(
                    ctx.id, "migration",
                    f"frontend runtime shutting down during worker "
                    f"failover ({failure})",
                    "worker_unavailable",
                )
                return
            if ctx.expired():
                yield LLMEngineOutput.final_error(
                    ctx.id, "migration",
                    "deadline exceeded during worker failover",
                    "deadline_exceeded",
                )
                return
            fab = getattr(
                getattr(self.router, "client", None), "drt", None
            )
            fab = getattr(fab, "fabric", None)
            if (
                fab is not None
                and getattr(fab, "in_degraded_mode", False)
                and not getattr(fab, "failed_permanently", False)
            ):
                # control-plane blackout, not a worker failure: the fleet
                # is likely healthy, only the dispatch bus is dark. Hold
                # the replay (without burning its retry budget) until the
                # fabric heals — bounded by the deadline/kill checks above
                # each pass and by the client's own degraded budget.
                dtrace.event("degraded_hold", cause=failure)
                await fab.wait_connected(2.0)
                continue
            failures = 1 if progressed else failures + 1
            bad = attempt_ctx.metadata.get("worker_instance_id")
            if bad is not None:
                exclude.add(bad)
            if failures > self.max_retries or (emitted and not can_replay):
                yield LLMEngineOutput.final_error(
                    ctx.id, "migration",
                    f"request failed after {failures} attempt(s): {failure}",
                    "worker_failed",
                )
                return
            logger.warning(
                "request %s: worker %s failed mid-stream (%s) — replaying "
                "%d emitted token(s) onto another worker (attempt %d/%d)",
                ctx.id, bad, failure, len(emitted), failures,
                self.max_retries,
            )
            dtrace.event(
                "migration",
                failed_worker=f"{bad:x}" if bad is not None else None,
                emitted=len(emitted), cause=failure,
            )
            if dprov.enabled():
                dprov.record(
                    "remote", "migrate",
                    f"{bad:x}" if bad is not None else None,
                    reason="worker_failed", ctx=ctx,
                    emitted=len(emitted), cause=failure,
                    attempt=failures,
                )
            if emitted:
                req_dict = dict(req_dict)
                req_dict["token_ids"] = (
                    list(request.token_ids) + list(emitted)
                )
                extra = dict(req_dict.get("extra") or {})
                extra["resume_prompt_len"] = prompt_len
                req_dict["extra"] = extra
            if self.on_migration is not None:
                with contextlib.suppress(Exception):
                    self.on_migration()
            if no_instances:
                # every worker unreachable (mass restart): pause until the
                # discovery watch applies a change — a dead instance aging
                # out or a restarted worker registering — instead of
                # burning the retry budget against a stale instance list
                waiter = getattr(
                    self.router.client, "wait_instances_changed", None
                )
                if waiter is not None:
                    await waiter(2.0)
            # shared retry policy (runtime/backoff.py): exponential with
            # FULL jitter off the consecutive-failure count (progress
            # resets it above), capped at 2 s
            await asyncio.sleep(
                full_jitter_delay(failures, self.backoff_base_s, cap_s=2.0)
            )


class WorkerCapacityPoller:
    """Background scrape of aggregated worker `load_metrics` for one
    endpoint: feeds the frontend's AdmissionController with the fleet's
    total request slots (the base of the shed watermark), and — when a
    HealthScorer is wired — feeds each worker's self-reported phase
    histograms into the tail-tolerance plane and advances its score
    tick (the self-reported half of gray-failure detection)."""

    def __init__(
        self,
        component: Any,
        endpoint_id: EndpointId,
        interval_s: float = 2.0,
        health: Optional[Any] = None,  # telemetry.health.HealthScorer
    ) -> None:
        from dynamo_tpu.kv_router.publisher import KvMetricsAggregator

        self.aggregator = KvMetricsAggregator(component, endpoint_id)
        self.interval_s = interval_s
        self.health = health
        self.total_slots: Optional[int] = None
        self.waiting: int = 0
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        with contextlib.suppress(asyncio.CancelledError):
            while True:
                try:
                    per_worker = await self.aggregator.collect()
                    slots = sum(
                        m.worker_stats.request_total_slots
                        for m in per_worker.values()
                    )
                    self.waiting = sum(
                        m.worker_stats.num_requests_waiting
                        for m in per_worker.values()
                    )
                    self.total_slots = slots or None
                    if self.health is not None:
                        for wid, m in per_worker.items():
                            self.health.observe_worker_hists(
                                wid, m.phase_histograms
                            )
                except Exception:  # noqa: BLE001 — scrape gaps tolerated
                    self.total_slots = None
                if self.health is not None:
                    # tick even on a failed scrape: staleness must AGE
                    # scores, not freeze them
                    self.health.tick()
                await asyncio.sleep(self.interval_s)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task


class ModelWatcher:
    """Watches `models/` and keeps a ModelManager in sync.

    (reference discovery/watcher.rs:69-346)"""

    def __init__(
        self,
        drt: DistributedRuntime,
        manager: ModelManager,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
        kv_router_config: Optional[Any] = None,
        metrics: Optional[Any] = None,  # http ServiceMetrics
        admission: Optional[Any] = None,  # http AdmissionController
    ) -> None:
        self.drt = drt
        self.manager = manager
        self.router_mode = router_mode
        self.kv_router_config = kv_router_config
        self.metrics = metrics
        self.admission = admission
        self._task: Optional[asyncio.Task] = None
        self._watch = None
        self._clients: dict[str, Any] = {}  # endpoint str -> Client
        self._key_to_model: dict[str, str] = {}
        self._kv_routers: dict[str, Any] = {}
        self._capacity_pollers: dict[str, WorkerCapacityPoller] = {}
        # tail-tolerance plane: one HealthScorer + HedgeController per
        # worker endpoint (shared by the Client, the KV scheduler, and
        # the RemoteEngine so ejection and hedging see one truth)
        self._health: dict[str, Any] = {}
        self._hedgers: dict[str, Any] = {}
        # trace-export event-plane fallback: one ingest loop per worker
        # namespace (spans a torn-down stream's final frame couldn't carry)
        self._trace_subs: set[str] = set()
        self._trace_tasks: list[asyncio.Task] = []

    async def start(self) -> None:
        self._watch = await self.drt.fabric.watch_prefix(MODEL_ROOT)
        for ev in self._watch.initial:
            await self._on_put(ev.key, ev.value)
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def _make_eject_publisher(self, namespace: str):
        """Ejections are fleet events: publish on `health-status` so the
        planner converts them into capacity-loss pressure
        (note_capacity_loss -> substitute spawns) without importing the
        frontend."""

        def on_eject(worker_id: int, cause: str) -> None:
            async def _pub() -> None:
                with contextlib.suppress(Exception):
                    await self.drt.namespace(namespace).publish_event(
                        dhealth.HEALTH_SUBJECT,
                        {
                            "event": "ejected",
                            "worker": worker_id,
                            "cause": cause,
                        },
                    )

            with contextlib.suppress(RuntimeError):  # no loop (tests)
                asyncio.get_running_loop().create_task(_pub())

        return on_eject

    async def _ensure_trace_ingest(self, namespace: str) -> None:
        """Subscribe (once per namespace) to the workers' trace-export
        subject: the metrics-plane fallback for spans (and decision
        records) whose response stream was torn down before the final
        frame could carry them."""
        if namespace in self._trace_subs or not (
            dtrace.enabled() or dprov.enabled()
        ):
            return
        self._trace_subs.add(namespace)
        sub = await self.drt.namespace(namespace).subscribe_event(
            dtrace.EXPORT_SUBJECT
        )

        async def ingest_loop() -> None:
            import msgpack

            async for _subject, payload in sub:
                try:
                    data = msgpack.unpackb(payload, raw=False)
                    if dtrace.enabled():
                        dtrace.ingest(data.get("trace") or [])
                    if dprov.enabled():
                        dprov.ingest(data.get("decisions") or [])
                except Exception:  # noqa: BLE001 — malformed export
                    continue

        self._trace_tasks.append(
            asyncio.get_running_loop().create_task(ingest_loop())
        )

    async def stop(self) -> None:
        if self._watch is not None:
            await self._watch.cancel()
        if self._task is not None:
            self._task.cancel()
        for t in self._trace_tasks:
            t.cancel()
        self._trace_tasks.clear()
        for kv_router in self._kv_routers.values():
            await kv_router.close()
        self._kv_routers.clear()
        for poller in self._capacity_pollers.values():
            await poller.stop()
        self._capacity_pollers.clear()
        for client in self._clients.values():
            await client.close()
        self._clients.clear()

    async def _loop(self) -> None:
        assert self._watch is not None
        with contextlib.suppress(asyncio.CancelledError):
            async for ev in self._watch:
                try:
                    if ev.type == "put":
                        await self._on_put(ev.key, ev.value)
                    else:
                        await self._on_delete(ev.key)
                except Exception:  # noqa: BLE001 — keep watching
                    logger.exception("model watcher failed applying %s", ev.key)

    async def _on_put(self, key: str, value: bytes) -> None:
        entry = ModelEntry.from_bytes(value)
        if self.manager.get(entry.name) is not None:
            self._key_to_model[key] = entry.name
            self.manager.add_model(entry.name, self.manager.get(entry.name), ref=key)  # type: ignore[arg-type]
            return
        mdc = await ModelDeploymentCard.download(self.drt.fabric, entry.slug)
        eid = EndpointId.parse(entry.endpoint)
        endpoint = (
            self.drt.namespace(eid.namespace).component(eid.component).endpoint(eid.name)
        )
        await self._ensure_trace_ingest(eid.namespace)
        client = self._clients.get(entry.endpoint)
        if client is None:
            client = await endpoint.client()
            self._clients[entry.endpoint] = client
        health = self._health.get(entry.endpoint)
        if health is None:
            health = dhealth.HealthScorer(
                on_eject=self._make_eject_publisher(eid.namespace)
            )
            self._health[entry.endpoint] = health
            # latency-ejected workers leave round-robin/random selection
            # and migration replays alongside dead-worker exclusions
            client.health = health
        hedger = self._hedgers.get(entry.endpoint)
        if hedger is None:
            hedger = self._hedgers[entry.endpoint] = dhealth.HedgeController()
        if self.router_mode is RouterMode.KV:
            from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter

            kv_router = self._kv_routers.get(entry.endpoint)
            if kv_router is None:
                kv_router = KvRouter(
                    endpoint.component,
                    client,
                    block_size=mdc.kv_block_size,
                    config=self.kv_router_config,
                )
                await kv_router.start()
                kv_router.scheduler.health = health
                self._kv_routers[entry.endpoint] = kv_router
                if self.metrics is not None:
                    # in-process router: its hit accounting scrapes straight
                    # onto the frontend /metrics (dyn_llm_kv_hit_rate)
                    self.metrics.attach_kv_hit_stats(kv_router.scheduler)
            router = PushRouter(
                client, RouterMode.KV, selector=KvPushRouter(kv_router)
            )
        else:
            router = PushRouter(client, self.router_mode)
        # admin fan-out: POST /clear_kv_blocks on the frontend round-trips
        # every worker's clear_kv_blocks endpoint (ref clear_kv_blocks.rs:88)
        clear_endpoint = endpoint.component.endpoint("clear_kv_blocks")
        clear_client_box: dict[str, Any] = {}

        async def clear_fn() -> list[dict]:
            client_c = clear_client_box.get("c")
            if client_c is None:
                client_c = await clear_endpoint.client()
                clear_client_box["c"] = client_c
            results = []
            for iid in client_c.instance_ids():
                stream = None
                try:
                    stream = await client_c.direct({}, iid)
                    async for item in stream:
                        if item.data is not None:
                            results.append(
                                {"instance": iid, **dict(item.data)}
                            )
                            break
                except Exception as e:  # noqa: BLE001
                    results.append({"instance": iid, "error": str(e)})
                finally:
                    if stream is not None:
                        await stream.close()
            return results

        on_migration = None
        if self.metrics is not None:
            model_name = entry.name

            def on_migration() -> None:
                self.metrics.request_migrations.labels(model_name).inc()

        # epoch fencing: the frontend's registry of cluster-declared-dead
        # epochs (fence/ tombstones) — dispatch frames from a fenced
        # worker are refused and the stream migrates
        fences = None
        with contextlib.suppress(Exception):
            fences = await self.drt.fences()
        execution = ModelExecution(
            mdc,
            RemoteEngine(
                router,
                on_migration=on_migration,
                cancel_token=self.drt.token,
                fences=fences,
                health=health,
                hedger=hedger,
            ),
            clear_fn=clear_fn,
        )
        self.manager.add_model(entry.name, execution, ref=key)
        self._key_to_model[key] = entry.name
        if self.metrics is not None:
            # tail metric families (attach-once: first endpoint wins,
            # same contract as attach_kv_hit_stats)
            self.metrics.attach_health(health, hedger)
            # hedge losers ride the goodput waste taxonomy too — the only
            # frontend-attributable cause (the engine sees a loser as a
            # plain consumer disconnect); no engine ledger here, remote
            # workers report theirs via the fabric scrape
            self.metrics.attach_goodput(None, hedger)
        if entry.name not in self._capacity_pollers:
            # the poller doubles as the health plane's scrape loop, so it
            # runs with or without admission control
            poller = WorkerCapacityPoller(
                endpoint.component, eid, health=health
            )
            poller.start()
            self._capacity_pollers[entry.name] = poller
            if self.admission is not None:
                # admission watermark follows the fleet's slot count
                self.admission.set_capacity_fn(
                    entry.name, lambda p=poller: p.total_slots
                )
        logger.info("watcher wired model %s via %s", entry.name, entry.endpoint)

    async def _on_delete(self, key: str) -> None:
        model = self._key_to_model.pop(key, None)
        if model is None:
            return
        if self.manager.remove_ref(model, key):
            poller = self._capacity_pollers.pop(model, None)
            if poller is not None:
                await poller.stop()
            if self.admission is not None:
                self.admission.remove_capacity_fn(model)
