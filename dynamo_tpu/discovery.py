"""Model discovery: workers register models; frontends watch and wire chains.

Role-equivalent of lib/llm/src/discovery/{watcher,model_manager,model_entry}.rs
and the bindings' `register_llm`: a worker publishes its ModelDeploymentCard
to the fabric object store and writes a lease-bound kv entry under `models/`;
every frontend's ModelWatcher sees the entry, downloads the card, builds the
preprocessor -> router -> backend chain, and registers it with its
ModelManager. Lease death removes the entry and (on last ref) the model.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
from dataclasses import dataclass
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.http.service import ModelExecution, ModelManager
from dynamo_tpu.model_card import ModelDeploymentCard
from dynamo_tpu.pipeline.annotated import Annotated
from dynamo_tpu.pipeline.context import Context
from dynamo_tpu.pipeline.router import PushRouter, RouterMode
from dynamo_tpu.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime.component import Endpoint
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.protocols import MODEL_ROOT, EndpointId

logger = get_logger("dynamo_tpu.discovery")


@dataclass
class ModelEntry:
    """The kv record under models/ (reference discovery/model_entry.rs)."""

    name: str
    slug: str
    endpoint: str  # dyn://ns.comp.ep
    model_type: str = "both"

    def to_bytes(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "ModelEntry":
        d = json.loads(raw)
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


async def register_llm(
    drt: DistributedRuntime,
    endpoint: Endpoint,
    mdc: ModelDeploymentCard,
    lease_id: Optional[int] = None,
) -> str:
    """Publish the model card + discovery entry for a serving worker.

    Returns the kv key (which dies with the lease)."""
    await mdc.publish(drt.fabric)
    lid = lease_id if lease_id is not None else drt.primary_lease
    entry = ModelEntry(
        name=mdc.name,
        slug=mdc.slug,
        endpoint=str(endpoint.id),
        model_type=mdc.model_type,
    )
    key = f"{MODEL_ROOT}{mdc.slug}:{lid:x}"
    await drt.fabric.kv_put(key, entry.to_bytes(), lease_id=lid)
    logger.info("registered model %s -> %s", mdc.name, entry.endpoint)
    return key


class RemoteEngine:
    """EngineFn adapter: forwards PreprocessedRequests over a PushRouter and
    yields LLMEngineOutput deltas from the response stream."""

    def __init__(self, router: PushRouter) -> None:
        self.router = router

    async def __call__(
        self, request: PreprocessedRequest, ctx: Context
    ) -> AsyncIterator[LLMEngineOutput]:
        stream = await self.router.generate(request.to_dict(), ctx)
        try:
            async for item in stream:
                if item.is_error():
                    raise RuntimeError(item.error_message() or "worker error")
                if item.data is not None:
                    yield LLMEngineOutput.from_dict(item.data)
        finally:
            await stream.close()


class ModelWatcher:
    """Watches `models/` and keeps a ModelManager in sync.

    (reference discovery/watcher.rs:69-346)"""

    def __init__(
        self,
        drt: DistributedRuntime,
        manager: ModelManager,
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
        kv_router_config: Optional[Any] = None,
    ) -> None:
        self.drt = drt
        self.manager = manager
        self.router_mode = router_mode
        self.kv_router_config = kv_router_config
        self._task: Optional[asyncio.Task] = None
        self._watch = None
        self._clients: dict[str, Any] = {}  # endpoint str -> Client
        self._key_to_model: dict[str, str] = {}
        self._kv_routers: dict[str, Any] = {}

    async def start(self) -> None:
        self._watch = await self.drt.fabric.watch_prefix(MODEL_ROOT)
        for ev in self._watch.initial:
            await self._on_put(ev.key, ev.value)
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._watch is not None:
            await self._watch.cancel()
        if self._task is not None:
            self._task.cancel()
        for kv_router in self._kv_routers.values():
            await kv_router.close()
        self._kv_routers.clear()
        for client in self._clients.values():
            await client.close()
        self._clients.clear()

    async def _loop(self) -> None:
        assert self._watch is not None
        with contextlib.suppress(asyncio.CancelledError):
            async for ev in self._watch:
                try:
                    if ev.type == "put":
                        await self._on_put(ev.key, ev.value)
                    else:
                        await self._on_delete(ev.key)
                except Exception:  # noqa: BLE001 — keep watching
                    logger.exception("model watcher failed applying %s", ev.key)

    async def _on_put(self, key: str, value: bytes) -> None:
        entry = ModelEntry.from_bytes(value)
        if self.manager.get(entry.name) is not None:
            self._key_to_model[key] = entry.name
            self.manager.add_model(entry.name, self.manager.get(entry.name), ref=key)  # type: ignore[arg-type]
            return
        mdc = await ModelDeploymentCard.download(self.drt.fabric, entry.slug)
        eid = EndpointId.parse(entry.endpoint)
        endpoint = (
            self.drt.namespace(eid.namespace).component(eid.component).endpoint(eid.name)
        )
        client = self._clients.get(entry.endpoint)
        if client is None:
            client = await endpoint.client()
            self._clients[entry.endpoint] = client
        if self.router_mode is RouterMode.KV:
            from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter

            kv_router = self._kv_routers.get(entry.endpoint)
            if kv_router is None:
                kv_router = KvRouter(
                    endpoint.component,
                    client,
                    block_size=mdc.kv_block_size,
                    config=self.kv_router_config,
                )
                await kv_router.start()
                self._kv_routers[entry.endpoint] = kv_router
            router = PushRouter(
                client, RouterMode.KV, selector=KvPushRouter(kv_router)
            )
        else:
            router = PushRouter(client, self.router_mode)
        # admin fan-out: POST /clear_kv_blocks on the frontend round-trips
        # every worker's clear_kv_blocks endpoint (ref clear_kv_blocks.rs:88)
        clear_endpoint = endpoint.component.endpoint("clear_kv_blocks")
        clear_client_box: dict[str, Any] = {}

        async def clear_fn() -> list[dict]:
            client_c = clear_client_box.get("c")
            if client_c is None:
                client_c = await clear_endpoint.client()
                clear_client_box["c"] = client_c
            results = []
            for iid in client_c.instance_ids():
                stream = None
                try:
                    stream = await client_c.direct({}, iid)
                    async for item in stream:
                        if item.data is not None:
                            results.append(
                                {"instance": iid, **dict(item.data)}
                            )
                            break
                except Exception as e:  # noqa: BLE001
                    results.append({"instance": iid, "error": str(e)})
                finally:
                    if stream is not None:
                        await stream.close()
            return results

        execution = ModelExecution(
            mdc, RemoteEngine(router), clear_fn=clear_fn
        )
        self.manager.add_model(entry.name, execution, ref=key)
        self._key_to_model[key] = entry.name
        logger.info("watcher wired model %s via %s", entry.name, entry.endpoint)

    async def _on_delete(self, key: str) -> None:
        model = self._key_to_model.pop(key, None)
        if model is None:
            return
        self.manager.remove_ref(model, key)
